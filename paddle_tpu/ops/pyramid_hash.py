"""pyramid_hash — the last reference op family member
(operators/pyramid_hash_op.cc): n-gram pyramid hashing embeddings for
text matching, with bloom-filter white/black lists.

Host op (CPU in the reference too — ragged windows + byte-level hashing):
- XXH32 over the FLOAT-cast id bytes picks rand_len-wide rows of W with
  the reference's rolling seed schedule (hash_embedding_ff, :226)
- white/black lists are the reference's packed bloomfilter blobs
  (math/bloomfilter.h: magic/m/k/count + bit vector; murmur3_x64_128
  membership probes) — :func:`bloom_create`/:func:`bloom_add` build
  wire-compatible blobs for tests/tools
- padded convention: X [B, T] int ids + optional Length; Out
  [B, maxW, num_emb] with per-sequence window counts in Length out.
"""
from __future__ import annotations

import struct

import numpy as np

from ..framework.executor import register_host_op
from .misc_extra import xxh64  # noqa: F401 (sibling hash util)

_M64 = (1 << 64) - 1
_MAGIC = 17070416

# ---------------------------------------------------------------------------
# XXH32 (xxhash spec; hash_embedding_ff uses XXH32(key, len, seed))
# ---------------------------------------------------------------------------

_P32_1 = 2654435761
_P32_2 = 2246822519
_P32_3 = 3266489917
_P32_4 = 668265263
_P32_5 = 374761393
_M32 = 0xFFFFFFFF


def _rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & _M32


def xxh32(data: bytes, seed: int = 0) -> int:
    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + _P32_1 + _P32_2) & _M32
        v2 = (seed + _P32_2) & _M32
        v3 = seed & _M32
        v4 = (seed - _P32_1) & _M32
        while i <= n - 16:
            for j in range(4):
                (lane,) = struct.unpack_from("<I", data, i + 4 * j)
                if j == 0:
                    v1 = (_rotl32((v1 + lane * _P32_2) & _M32, 13)
                          * _P32_1) & _M32
                elif j == 1:
                    v2 = (_rotl32((v2 + lane * _P32_2) & _M32, 13)
                          * _P32_1) & _M32
                elif j == 2:
                    v3 = (_rotl32((v3 + lane * _P32_2) & _M32, 13)
                          * _P32_1) & _M32
                else:
                    v4 = (_rotl32((v4 + lane * _P32_2) & _M32, 13)
                          * _P32_1) & _M32
            i += 16
        h = (_rotl32(v1, 1) + _rotl32(v2, 7) + _rotl32(v3, 12)
             + _rotl32(v4, 18)) & _M32
    else:
        h = (seed + _P32_5) & _M32
    h = (h + n) & _M32
    while i <= n - 4:
        (k,) = struct.unpack_from("<I", data, i)
        h = (_rotl32((h + k * _P32_3) & _M32, 17) * _P32_4) & _M32
        i += 4
    while i < n:
        h = (_rotl32((h + data[i] * _P32_5) & _M32, 11) * _P32_1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * _P32_2) & _M32
    h ^= h >> 13
    h = (h * _P32_3) & _M32
    h ^= h >> 16
    return h


# ---------------------------------------------------------------------------
# murmur3_x64_128 + bloom blobs (math/bloomfilter.h)
# ---------------------------------------------------------------------------


def _fmix64(k):
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _M64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _M64
    k ^= k >> 33
    return k


def _rotl64(x, r):
    return ((x << r) | (x >> (64 - r))) & _M64


def murmur3_x64_128(data: bytes, seed: int):
    """Reference-faithful variant INCLUDING its tail quirk: the tail is
    read as two unconditional 8-byte loads (so the buffer is expected to
    be padded; we zero-pad) masked per len&15."""
    n = len(data)
    nblocks = n // 16
    h1 = h2 = seed & _M64
    c1 = 0x87C37B91114253D5
    c2 = 0x4CF5AD432745937F
    for i in range(nblocks):
        k1, k2 = struct.unpack_from("<QQ", data, i * 16)
        k1 = (_rotl64((k1 * c1) & _M64, 31) * c2) & _M64
        h1 ^= k1
        h1 = (((_rotl64(h1, 27) + h2) & _M64) * 5 + 0x52DCE729) & _M64
        k2 = (_rotl64((k2 * c2) & _M64, 33) * c1) & _M64
        h2 ^= k2
        h2 = (((_rotl64(h2, 31) + h1) & _M64) * 5 + 0x38495AB5) & _M64
    tail = data[nblocks * 16:] + b"\x00" * 16
    t0, t1 = struct.unpack_from("<QQ", tail, 0)
    flag = n & 15
    if flag and flag <= 8:
        t0 &= (0xFFFFFFFFFFFFFFFF >> ((8 - flag) << 3))
    elif flag > 8:
        t1 &= (0x00FFFFFFFFFFFFFF >> ((15 - flag) << 3))
        nk2 = (_rotl64((t1 * c2) & _M64, 33) * c1) & _M64
        h2 ^= nk2
    if flag:
        nk1 = (_rotl64((t0 * c1) & _M64, 31) * c2) & _M64
        h1 ^= nk1
    h1 ^= n
    h2 ^= n
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    return h1, h2


def bloom_create(m_bits: int, k: int = 3) -> np.ndarray:
    """An empty reference-layout bloom blob as a float32 array (the op's
    storage dtype). Layout: 4 uint64 header + bit vector."""
    nbytes = 32 + (m_bits + 7) // 8
    nbytes = (nbytes + 3) // 4 * 4
    buf = bytearray(nbytes)
    struct.pack_into("<QQQQ", buf, 0, _MAGIC, m_bits, k, 0)
    return np.frombuffer(bytes(buf), np.float32).copy()


def bloom_add(blob: np.ndarray, key: bytes) -> None:
    buf = bytearray(blob.tobytes())
    _, m, k, _ = struct.unpack_from("<QQQQ", buf, 0)
    for i in range(k):
        h1, h2 = murmur3_x64_128(key, i)
        for pos in (h1 % m, h2 % m):
            buf[32 + (pos >> 3)] |= 0x1 << (0x7 - (pos & 0x7))
    blob[:] = np.frombuffer(bytes(buf), np.float32)


def _bloom_get(buf: bytes, key: bytes) -> bool:
    magic, m, k, _ = struct.unpack_from("<QQQQ", buf, 0)
    if magic != _MAGIC:
        raise ValueError("bloom filter blob: bad magic")
    for i in range(k):
        h1, h2 = murmur3_x64_128(key, i)
        for pos in (h1 % m, h2 % m):
            if not (buf[32 + (pos >> 3)] & (0x1 << (0x7 - (pos & 0x7)))):
                return False
    return True


# ---------------------------------------------------------------------------
# the op
# ---------------------------------------------------------------------------


@register_host_op("pyramid_hash")
def pyramid_hash(scope, op, exe):
    import jax.numpy as jnp

    x = np.asarray(scope.find_var(op.input("X")[0]))
    w = np.asarray(scope.find_var(op.input("W")[0]))
    white = (np.asarray(scope.find_var(op.input("WhiteList")[0]))
             if op.input("WhiteList") else None)
    black = (np.asarray(scope.find_var(op.input("BlackList")[0]))
             if op.input("BlackList") else None)
    num_emb = int(op.attr("num_emb"))
    rand_len = int(op.attr("rand_len"))
    space_len = int(op.attr("space_len"))
    layers = int(op.attr("pyramid_layer", 2))
    use_filter = bool(op.attr("use_filter", True))
    white_len = int(op.attr("white_list_len", 0))
    black_len = int(op.attr("black_list_len", 0))
    is_training = int(op.attr("is_training", 0))
    drop_p = float(op.attr("drop_out_percent", 0.0))
    seed = int(op.attr("seed", 0))
    rng = np.random.RandomState(seed or 1)

    if x.ndim == 1:
        x = x[None, :]
    B, T = x.shape
    if op.input("Length"):
        lens = np.asarray(scope.find_var(op.input("Length")[0])) \
            .reshape(-1).astype(int)
    else:
        lens = np.full((B,), T, int)
    wbuf = white.tobytes() if (use_filter and white_len and
                               white is not None) else None
    bbuf = black.tobytes() if (use_filter and black_len and
                               black is not None) else None

    xf = x.astype(np.float32)
    max_w = max(1, sum(max(0, T - il) for il in range(1, layers)))
    out = np.zeros((B, max_w, num_emb), w.dtype)
    counts = np.zeros((B,), np.int64)
    for b in range(B):
        wlen = int(lens[b])
        if wlen < 2:
            continue
        k = 0
        for ilayer in range(1, min(layers, wlen)):
            for l in range(wlen - ilayer):
                term = xf[b, l:l + ilayer + 1].tobytes()
                keep = True
                if wbuf is not None:
                    keep = _bloom_get(wbuf, term)
                if keep and bbuf is not None:
                    keep = not _bloom_get(bbuf, term)
                if keep and is_training and drop_p > 0:
                    keep = rng.rand() >= drop_p
                if not keep:
                    continue
                row = np.empty(num_emb, w.dtype)
                pos1 = xxh32(term, 0) % space_len
                pos2 = xxh32(term, rand_len) % space_len
                for j in range(0, num_emb, rand_len):
                    pos3 = xxh32(term, j + 2 * rand_len) % space_len
                    row[j:j + rand_len] = w[pos1:pos1 + rand_len, 0] \
                        if w.ndim == 2 and w.shape[1] == 1 \
                        else w.reshape(-1)[pos1:pos1 + rand_len]
                    pos1, pos2 = pos2, pos3
                out[b, k] = row
                k += 1
        counts[b] = k
    scope.set_var(op.output("Out")[0], jnp.asarray(out))
    if op.output("DropPos"):
        scope.set_var(op.output("DropPos")[0],
                      jnp.asarray(counts.reshape(-1, 1)))
    if op.output("X_Temp_Out"):
        scope.set_var(op.output("X_Temp_Out")[0], jnp.asarray(xf))
