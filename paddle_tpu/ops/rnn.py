"""Recurrent ops — parity with the reference RNN surface
(operators/cudnn_lstm_op.cc layers.lstm; operators/gru_op.cc;
operators/lstm_op.cc dynamic_lstm).

TPU-first design: the recurrence is ONE ``lax.scan`` (a single compiled XLA
While with an MXU matmul body) instead of the reference's per-timestep kernel
launches or a T-times unrolled graph.  Weights arrive as one packed blob per
stack (the cudnn_lstm "W" convention) so multi-layer stacks stay a single
parameter.  Sequence-length masking replaces LoD raggedness: padded steps
carry the last valid state through (dynamic_lstm semantics on static shapes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _lstm_layer_sizes(in_dim: int, hidden: int):
    # [Wx (in,4H), Wh (H,4H), b (4H)]
    return in_dim * 4 * hidden, hidden * 4 * hidden, 4 * hidden


def lstm_blob_size(in_dim: int, hidden: int, num_layers: int,
                   num_directions: int = 1) -> int:
    total = 0
    d = in_dim
    for _ in range(num_layers):
        wx, wh, b = _lstm_layer_sizes(d, hidden)
        total += (wx + wh + b) * num_directions
        d = hidden * num_directions
    return total


def _reverse_padded(x, seq_len):
    """Reverse each row's valid prefix along time, leaving padding in place
    (the bidirectional backward pass must not start inside the padding)."""
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)[None, :]                        # [1,T]
    if seq_len is None:
        src = (T - 1 - t) * jnp.ones((B, 1), jnp.int32)
    else:
        L = seq_len.astype(jnp.int32)[:, None]        # [B,1]
        src = jnp.where(t < L, L - 1 - t, t)
    return jnp.take_along_axis(
        x, src.reshape(B, T, *([1] * (x.ndim - 2))).astype(jnp.int32), axis=1)


def _scan_lstm_layer(x, h0, c0, wx, wh, b, seq_len=None):
    """x: [B,T,D]; returns (out [B,T,H], hT, cT)."""
    B, T, D = x.shape
    H = h0.shape[-1]

    def step(carry, inp):
        h, c = carry
        xt, t = inp
        gates = xt @ wx + h @ wh + b           # [B, 4H]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        if seq_len is not None:
            live = (t < seq_len)[:, None]      # [B,1]
            h_new = jnp.where(live, h_new, h)
            c_new = jnp.where(live, c_new, c)
        return (h_new, c_new), h_new

    xs = jnp.swapaxes(x, 0, 1)                 # [T,B,D]
    ts = jnp.arange(T)
    (hT, cT), outs = jax.lax.scan(step, (h0, c0), (xs, ts))
    return jnp.swapaxes(outs, 0, 1), hT, cT


@register_op("cudnn_lstm", diff_inputs=("Input", "W", "InitH", "InitC"))
def cudnn_lstm(ctx, op, ins):
    """Multi-layer LSTM over a packed weight blob — layers.lstm
    (fluid/layers/rnn.py lstm -> cudnn_lstm_op.cc)."""
    x = ins["Input"][0]                         # [B,T,D]
    w = ins["W"][0]                             # packed blob
    h0 = ins["InitH"][0]                        # [L,B,H]
    c0 = ins["InitC"][0]
    seq_len = ins.get("SequenceLength", [None])[0]
    num_layers = int(op.attr("num_layers", 1))
    hidden = int(op.attr("hidden_size"))
    dropout_prob = float(op.attr("dropout_prob", 0.0))
    is_test = bool(op.attr("is_test", False))
    is_bidirec = bool(op.attr("is_bidirec", False))
    directions = 2 if is_bidirec else 1

    out = x
    hs, cs = [], []
    off = 0
    d = x.shape[-1]
    for layer in range(num_layers):
        dir_outs = []
        for direction in range(directions):
            nwx, nwh, nb = _lstm_layer_sizes(d, hidden)
            wx = w[off:off + nwx].reshape(d, 4 * hidden); off += nwx
            wh = w[off:off + nwh].reshape(hidden, 4 * hidden); off += nwh
            b = w[off:off + nb]; off += nb
            state = layer * directions + direction
            inp = out if direction == 0 else _reverse_padded(out, seq_len)
            o, hT, cT = _scan_lstm_layer(inp, h0[state], c0[state],
                                         wx, wh, b, seq_len)
            if direction == 1:
                o = _reverse_padded(o, seq_len)
            dir_outs.append(o)
            hs.append(hT)
            cs.append(cT)
        out = (dir_outs[0] if directions == 1
               else jnp.concatenate(dir_outs, axis=-1))
        d = hidden * directions
        if dropout_prob and not is_test and layer < num_layers - 1:
            # fold in the layer index: rng_for(op) is constant across the
            # python loop and identical masks at every depth would correlate
            key = jax.random.fold_in(ctx.rng_for(op), layer)
            keep = jax.random.bernoulli(key, 1 - dropout_prob, out.shape)
            out = jnp.where(keep, out / (1 - dropout_prob), 0.0)
    return {"Out": out, "LastH": jnp.stack(hs), "LastC": jnp.stack(cs)}


@register_op("fused_gru", diff_inputs=("Input", "WeightX", "WeightH", "Bias",
                                       "InitH"))
def fused_gru(ctx, op, ins):
    """Single-layer GRU (gru_op.cc semantics, batch-major static shapes).
    Gate layout follows the reference: [update u | reset r | candidate c]."""
    x = ins["Input"][0]                         # [B,T,D]
    wx = ins["WeightX"][0]                      # [D,3H]
    wh = ins["WeightH"][0]                      # [H,3H]
    b = ins["Bias"][0] if "Bias" in ins else None
    h0 = ins["InitH"][0]                        # [B,H]
    seq_len = ins.get("SequenceLength", [None])[0]
    H = wh.shape[0]

    def step(h, inp):
        xt, t = inp
        gx = xt @ wx + (b if b is not None else 0.0)    # [B,3H]
        gh = h @ wh
        u = jax.nn.sigmoid(gx[:, :H] + gh[:, :H])
        r = jax.nn.sigmoid(gx[:, H:2 * H] + gh[:, H:2 * H])
        c = jnp.tanh(gx[:, 2 * H:] + r * gh[:, 2 * H:])
        h_new = u * h + (1.0 - u) * c
        if seq_len is not None:
            live = (t < seq_len)[:, None]
            h_new = jnp.where(live, h_new, h)
        return h_new, h_new

    xs = jnp.swapaxes(x, 0, 1)
    ts = jnp.arange(x.shape[1])
    hT, outs = jax.lax.scan(step, h0, (xs, ts))
    return {"Out": jnp.swapaxes(outs, 0, 1), "LastH": hT}
