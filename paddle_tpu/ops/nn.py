"""Neural-net op lowerings: conv/pool/norm/softmax/dropout/embedding/losses.

Capability parity with the dense-NN portion of reference
paddle/fluid/operators/ (conv_op.cc + conv_cudnn_op.cu, pool_op, batch_norm_op,
layer_norm_op, softmax_op, dropout_op, lookup_table_op, activation_op,
cross_entropy_op, softmax_with_cross_entropy_op, …). Convs/matmuls lower to
lax conv/dot so XLA tiles them onto the MXU; everything elementwise fuses.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.core import dtype_to_jax
from ..framework.registry import register_op

# ---------------------------------------------------------------------------
# Activations (reference operators/activation_op.cc — one templated family)
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softplus": jax.nn.softplus,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "hard_swish": lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0,
}
for _name, _fn in _ACTS.items():
    register_op(_name)(
        (lambda fn: lambda ctx, op, ins: {"Out": fn(ins["X"][0])})(_fn)
    )


@register_op("leaky_relu")
def leaky_relu(ctx, op, ins):
    alpha = op.attr("alpha", 0.02)
    return {"Out": jax.nn.leaky_relu(ins["X"][0], negative_slope=alpha)}


@register_op("elu")
def elu(ctx, op, ins):
    return {"Out": jax.nn.elu(ins["X"][0], alpha=op.attr("alpha", 1.0))}


@register_op("gelu")
def gelu(ctx, op, ins):
    return {"Out": jax.nn.gelu(ins["X"][0], approximate=op.attr("approximate", False))}


@register_op("prelu")
def prelu(ctx, op, ins):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = op.attr("mode", "all")
    if mode == "channel" and alpha.ndim == 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register_op("softmax")
def softmax(ctx, op, ins):
    axis = op.attr("axis", -1)
    return {"Out": jax.nn.softmax(ins["X"][0], axis=axis)}


@register_op("log_softmax")
def log_softmax(ctx, op, ins):
    axis = op.attr("axis", -1)
    return {"Out": jax.nn.log_softmax(ins["X"][0], axis=axis)}


@register_op("softmax_with_cross_entropy", diff_inputs=("Logits",))
def softmax_with_cross_entropy(ctx, op, ins):
    """reference operators/softmax_with_cross_entropy_op.cc — fused, stable.

    attrs['vocab_chunk'] > 0 selects the chunked lowering variant: the loss
    (and its Logits grad, via custom_vjp) is computed blockwise over the
    class axis with an online logsumexp, so the f32 log-softmax/softmax
    intermediates never materialize at [batch*time, V] — only the Loss
    output is produced (no Softmax), hard labels, last-axis only.
    """
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    axis = op.attr("axis", -1)
    soft_label = op.attr("soft_label", False)
    ignore_index = op.attr("ignore_index", -100)
    vocab_chunk = int(op.attr("vocab_chunk", 0) or 0)
    if vocab_chunk and not soft_label and axis in (-1, logits.ndim - 1):
        from .pallas_kernels import chunked_softmax_ce_from_logits

        v = logits.shape[-1]
        vc = min(vocab_chunk, v)
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=-1)
        lbl = lbl.astype(jnp.int32)
        rows = logits.reshape(-1, v)
        pad = (-v) % vc
        if pad:  # -inf columns drop out of the logsumexp and get zero grad
            rows = jnp.concatenate(
                [rows, jnp.full((rows.shape[0], pad), -jnp.inf,
                                rows.dtype)], axis=1)
        ce = chunked_softmax_ce_from_logits(
            rows, jnp.clip(lbl, 0, v - 1).reshape(-1), vc)
        loss = ce.reshape(lbl.shape)[..., None].astype(logits.dtype)
        loss = jnp.where(lbl[..., None] != ignore_index, loss, 0.0)
        return {"Loss": loss}
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.clip(lbl, 0, logits.shape[axis] - 1), axis), axis=axis
        )
        loss = -picked
        mask = jnp.expand_dims(lbl, axis) != ignore_index
        loss = jnp.where(mask, loss, 0.0)
    return {"Softmax": jnp.exp(logp), "Loss": loss}


@register_op("cross_entropy", diff_inputs=("X",))
def cross_entropy(ctx, op, ins):
    """reference operators/cross_entropy_op.cc: X is probabilities."""
    x = ins["X"][0]
    label = ins["Label"][0]
    soft_label = op.attr("soft_label", False)
    eps = 1e-12
    if soft_label:
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lbl = label.astype(jnp.int32)
        if lbl.ndim == x.ndim:
            lbl = jnp.squeeze(lbl, -1)
        picked = jnp.take_along_axis(x, lbl[..., None], axis=-1)
        loss = -jnp.log(picked + eps)
    return {"Y": loss}


@register_op("bce_loss", diff_inputs=("X",))
def bce_loss(ctx, op, ins):
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-12
    return {"Out": -(label * jnp.log(x + eps) + (1 - label) * jnp.log(1 - x + eps))}


@register_op("sigmoid_cross_entropy_with_logits", diff_inputs=("X",))
def sigmoid_ce_logits(ctx, op, ins):
    x, label = ins["X"][0], ins["Label"][0]
    ignore_index = op.attr("ignore_index", -100)
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = label != ignore_index
    loss = jnp.where(mask, loss, 0.0)
    if op.attr("normalize", False):
        norm = jnp.maximum(jnp.sum(mask.astype(x.dtype)), 1.0)
        loss = loss / norm
    return {"Out": loss}


@register_op("smooth_l1_loss", diff_inputs=("X",))
def smooth_l1_loss(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = op.attr("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    return {"Diff": diff, "Out": jnp.sum(elem, axis=tuple(range(1, x.ndim)), keepdims=False)[..., None]}


@register_op("huber_loss", diff_inputs=("X",))
def huber_loss(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    delta = op.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Residual": r, "Out": out}


@register_op("mse_loss", diff_inputs=("X",))
def mse_loss(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.square(x - y)}


@register_op("kldiv_loss", diff_inputs=("X",))
def kldiv_loss(ctx, op, ins):
    x, target = ins["X"][0], ins["Target"][0]
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    loss = jnp.where(target > 0, loss, 0.0)
    red = op.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    return {"Loss": loss}


# ---------------------------------------------------------------------------
# Convolution / pooling — MXU ops (reference conv_op.cc, conv_cudnn_op.cu,
# pool_op.cc; cuDNN algo search is replaced by XLA's conv emitter)
# ---------------------------------------------------------------------------


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def _conv_padding(padding, ndim):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    p = _pair(padding, ndim)
    if len(p) == ndim:
        return [(int(x), int(x)) for x in p]
    if len(p) == 2 * ndim:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(ndim)]
    raise ValueError(f"bad padding {padding}")


@register_op("conv2d", diff_inputs=("Input", "Filter"))
def conv2d(ctx, op, ins):
    x = ins["Input"][0]  # NCHW
    w = ins["Filter"][0]  # OIHW (I = C/groups)
    groups = op.attr("groups", 1) or 1
    strides = _pair(op.attr("strides", [1, 1]))
    dilations = _pair(op.attr("dilations", [1, 1]))
    padding = _conv_padding(op.attr("paddings", [0, 0]), 2)
    data_format = op.attr("data_format", "NCHW")
    if data_format in ("NHWC",):
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "OIHW", "NHWC"))
    else:
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=groups,
        # no preferred_element_type: its transpose rule mixes an f32 cotangent
        # with the low-precision filter and lax.conv rejects mixed dtypes;
        # TPU convs accumulate bf16 inputs in f32 inside the MXU regardless
    ).astype(x.dtype)
    return {"Output": out}


@register_op("depthwise_conv2d", diff_inputs=("Input", "Filter"))
def depthwise_conv2d(ctx, op, ins):
    # groups == channels; same lowering, XLA specializes
    return conv2d(ctx, op, ins)


@register_op("conv3d", diff_inputs=("Input", "Filter"))
def conv3d(ctx, op, ins):
    x, w = ins["Input"][0], ins["Filter"][0]
    groups = op.attr("groups", 1) or 1
    strides = _pair(op.attr("strides", [1, 1, 1]), 3)
    dilations = _pair(op.attr("dilations", [1, 1, 1]), 3)
    padding = _conv_padding(op.attr("paddings", [0, 0, 0]), 3)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups,
    ).astype(x.dtype)
    return {"Output": out}


@register_op("conv2d_transpose", diff_inputs=("Input", "Filter"))
def conv2d_transpose(ctx, op, ins):
    x, w = ins["Input"][0], ins["Filter"][0]  # NCHW, IOHW in paddle
    out = conv_transpose_nd(
        x, w, _pair(op.attr("strides", [1, 1])),
        _pair(op.attr("paddings", [0, 0])),
        _pair(op.attr("dilations", [1, 1])),
        op.attr("groups", 1) or 1, nd=2)
    return {"Output": out}


def conv_transpose_nd(x, w, strides, paddings, dilations, groups, nd):
    """Transposed conv as an lhs-dilated conv. w: [Cin, Cout/g, *k] (paddle
    layout) -> rhs [Cout, Cin/g, *k] via per-group rearrangement, spatially
    flipped. Shared by conv2d_transpose / conv3d_transpose /
    depthwise_conv2d_transpose (ops/nn_extra.py)."""
    k = w.shape[2:]
    cin, cout_g = w.shape[0], w.shape[1]
    wg = w.reshape((groups, cin // groups, cout_g) + k)
    wg = jnp.swapaxes(wg, 1, 2)                      # [g, Cout/g, Cin/g, k]
    w_t = wg.reshape((groups * cout_g, cin // groups) + k)
    w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + nd)))
    pad = [(dilations[i] * (k[i] - 1) - paddings[i],
            dilations[i] * (k[i] - 1) - paddings[i]) for i in range(nd)]
    dn = lax.conv_dimension_numbers(
        x.shape, w_t.shape,
        (("NCHW", "OIHW", "NCHW") if nd == 2 else
         ("NCDHW", "OIDHW", "NCDHW")))
    out = lax.conv_general_dilated(
        x, w_t, window_strides=(1,) * nd, padding=pad,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)
    return out.astype(x.dtype)


@register_op("pool2d", diff_inputs=("X",))
def pool2d(ctx, op, ins):
    x = ins["X"][0]  # NCHW
    ptype = op.attr("pooling_type", "max")
    ksize = _pair(op.attr("ksize", [2, 2]))
    strides = _pair(op.attr("strides", [1, 1]))
    paddings = _pair(op.attr("paddings", [0, 0]))
    global_pool = op.attr("global_pooling", False)
    adaptive = op.attr("adaptive", False)
    exclusive = op.attr("exclusive", True)
    ceil_mode = op.attr("ceil_mode", False)

    if global_pool or (adaptive and tuple(ksize) == (1, 1)):
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x, axis=(2, 3), keepdims=True)}
    if adaptive:
        oh, ow = ksize
        h, w = x.shape[2], x.shape[3]
        assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible sizes"
        x5 = x.reshape(x.shape[0], x.shape[1], oh, h // oh, ow, w // ow)
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x5, axis=(3, 5))}

    window = (1, 1) + tuple(ksize)
    strides4 = (1, 1) + tuple(strides)
    pad4 = [(0, 0), (0, 0)] + [(p, p) for p in paddings]
    if ceil_mode:
        # pad the right edge so the last window fits
        new_pad = []
        for i, (lo, hi) in enumerate(pad4):
            if i >= 2:
                size = x.shape[i] + lo + hi
                rem = (size - window[i]) % strides4[i]
                if rem:
                    hi += strides4[i] - rem
            new_pad.append((lo, hi))
        pad4 = new_pad
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides4, pad4)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides4, pad4)
        if exclusive and any(p for pp in pad4 for p in pp):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides4, pad4)
            out = summed / counts
        else:
            out = summed / float(np.prod(ksize))
    return {"Out": out.astype(x.dtype)}


# ---------------------------------------------------------------------------
# Normalization ops
# ---------------------------------------------------------------------------


def _batch_norm_impl(ctx, op, ins, sync_axis=None):
    """Shared batch_norm / sync_batch_norm lowering. With ``sync_axis`` the
    batch statistics are the GLOBAL mean/var over every rank of that mesh
    axis (one psum of [sum, sqsum] — reference sync_batch_norm_op.cc reduces
    the same pair over NCCL)."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean_in, var_in = ins["Mean"][0], ins["Variance"][0]
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    is_test = op.attr("is_test", False) or op.attr("use_global_stats", False)
    data_layout = op.attr("data_layout", "NCHW")

    if data_layout == "NCHW":
        axes = (0,) + tuple(range(2, x.ndim))
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        shape = (1,) * (x.ndim - 1) + (-1,)

    if is_test:
        mean, var = mean_in, var_in
        saved_mean, saved_var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
    else:
        xf = x.astype(jnp.float32)
        if sync_axis is not None:
            cnt = float(np.prod([x.shape[a] for a in axes]))
            s = jax.lax.psum(jnp.sum(xf, axis=axes), sync_axis)
            sq = jax.lax.psum(jnp.sum(jnp.square(xf), axis=axes), sync_axis)
            n = cnt * jax.lax.psum(jnp.ones((), jnp.float32), sync_axis)
            mean = s / n
            var = sq / n - jnp.square(mean)
        else:
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
        mean_out = mean_in * momentum + mean * (1 - momentum)
        var_out = var_in * momentum + var * (1 - momentum)
        saved_mean, saved_var = mean, var

    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    y = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    y = y * scale.reshape(shape) + bias.reshape(shape)
    return {
        "Y": y.astype(x.dtype),
        "MeanOut": mean_out,
        "VarianceOut": var_out,
        "SavedMean": saved_mean,
        "SavedVariance": inv,
    }


@register_op("batch_norm", diff_inputs=("X", "Scale", "Bias"))
def batch_norm(ctx, op, ins):
    """reference operators/batch_norm_op.cc (+cudnn). NCHW or NC...; in
    training mode also emits updated moving stats (MeanOut/VarianceOut alias
    the persistable Mean/Variance vars, in-place by name in the env)."""
    return _batch_norm_impl(ctx, op, ins)


@register_op("sync_batch_norm", diff_inputs=("X", "Scale", "Bias"))
def sync_batch_norm(ctx, op, ins):
    """reference operators/sync_batch_norm_op.cc: batch_norm whose batch
    statistics (and, through the vjp's collective transposes, the grads) are
    reduced over the data-parallel mesh axis — small per-device batches
    normalize exactly like the merged global batch. Falls back to local
    stats when no dp mesh is active (single-device execution)."""
    axis = ctx.axis_name(op.attr("ring_id", 0))
    return _batch_norm_impl(ctx, op, ins, sync_axis=axis)


@register_op("layer_norm", diff_inputs=("X", "Scale", "Bias"))
def layer_norm(ctx, op, ins):
    """reference operators/layer_norm_op.cc: normalize over dims >= begin_norm_axis."""
    x = ins["X"][0]
    eps = op.attr("epsilon", 1e-5)
    bna = op.attr("begin_norm_axis", 1)
    axes = tuple(range(bna, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    if "Scale" in ins and ins["Scale"]:
        y = y * ins["Scale"][0].reshape((1,) * bna + x.shape[bna:]).astype(jnp.float32)
    if "Bias" in ins and ins["Bias"]:
        y = y + ins["Bias"][0].reshape((1,) * bna + x.shape[bna:]).astype(jnp.float32)
    return {
        "Y": y.astype(x.dtype),
        "Mean": jnp.squeeze(mean, axes),
        "Variance": jnp.squeeze(var, axes),
    }


@register_op("instance_norm", diff_inputs=("X", "Scale", "Bias"))
def instance_norm(ctx, op, ins):
    x = ins["X"][0]
    eps = op.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if "Scale" in ins and ins["Scale"]:
        y = y * ins["Scale"][0].reshape(shape)
    if "Bias" in ins and ins["Bias"]:
        y = y + ins["Bias"][0].reshape(shape)
    return {"Y": y, "SavedMean": jnp.squeeze(mean, axes), "SavedVariance": jnp.squeeze(var, axes)}


@register_op("group_norm", diff_inputs=("X", "Scale", "Bias"))
def group_norm(ctx, op, ins):
    x = ins["X"][0]  # NCHW
    g = op.attr("groups", 1)
    eps = op.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if "Scale" in ins and ins["Scale"]:
        y = y * ins["Scale"][0].reshape(shape)
    if "Bias" in ins and ins["Bias"]:
        y = y + ins["Bias"][0].reshape(shape)
    return {"Y": y, "Mean": jnp.reshape(mean, (n, g)), "Variance": jnp.reshape(var, (n, g))}


@register_op("l2_normalize", diff_inputs=("X",))
def l2_normalize(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", -1)
    eps = op.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


# ---------------------------------------------------------------------------
# Dropout — random; key derived from output names so grad replay is CSE-able
# ---------------------------------------------------------------------------


@register_op("dropout", diff_inputs=("X",), needs_rng=True)
def dropout(ctx, op, ins):
    x = ins["X"][0]
    p = op.attr("dropout_prob", 0.5)
    is_test = op.attr("is_test", False)
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    if is_test or p == 0.0:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        if is_test and impl == "upscale_in_train":
            out = x
        return {"Out": out, "Mask": jnp.ones_like(x, dtype=jnp.uint8)}
    key = ctx.rng_for(op)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {"Out": out.astype(x.dtype), "Mask": keep.astype(jnp.uint8)}


# ---------------------------------------------------------------------------
# Embedding (reference lookup_table_op.cc; sparse grad becomes dense
# scatter-add via vjp of take — on TPU a segment-sum, MXU-free)
# ---------------------------------------------------------------------------


@register_op("lookup_table", diff_inputs=("W",))
def lookup_table(ctx, op, ins):
    w = ins["W"][0]
    ids = ins["Ids"][0]
    padding_idx = op.attr("padding_idx", -1)
    sq = ids.shape[-1] == 1
    idx = jnp.squeeze(ids, -1) if sq and ids.ndim > 1 else ids
    idx = idx.astype(jnp.int32)
    out = jnp.take(w, jnp.clip(idx, 0, w.shape[0] - 1), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((idx == padding_idx)[..., None], 0.0, out)
    return {"Out": out}


@register_op("lookup_table_v2", diff_inputs=("W",))
def lookup_table_v2(ctx, op, ins):
    w = ins["W"][0]
    ids = ins["Ids"][0].astype(jnp.int32)
    padding_idx = op.attr("padding_idx", -1)
    out = jnp.take(w, jnp.clip(ids, 0, w.shape[0] - 1), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": out}


@register_op("one_hot", grad=None)
def one_hot(ctx, op, ins):
    ids = ins["X"][0]
    depth = op.attr("depth")
    if ids.shape and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    return {"Out": jax.nn.one_hot(ids.astype(jnp.int32), depth, dtype=jnp.float32)}


@register_op("one_hot_v2", grad=None)
def one_hot_v2(ctx, op, ins):
    ids = ins["X"][0]
    depth = op.attr("depth")
    return {"Out": jax.nn.one_hot(ids.astype(jnp.int32), depth, dtype=jnp.float32)}


# ---------------------------------------------------------------------------
# Interpolation / padding
# ---------------------------------------------------------------------------


@register_op("nearest_interp", diff_inputs=("X",))
def nearest_interp(ctx, op, ins):
    x = ins["X"][0]  # NCHW
    oh = op.attr("out_h", -1)
    ow = op.attr("out_w", -1)
    scale = op.attr("scale", 0.0)
    if scale and (oh <= 0 or ow <= 0):
        oh = int(x.shape[2] * scale)
        ow = int(x.shape[3] * scale)
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), method="nearest")
    return {"Out": out}


@register_op("bilinear_interp", diff_inputs=("X",))
def bilinear_interp(ctx, op, ins):
    x = ins["X"][0]
    oh = op.attr("out_h", -1)
    ow = op.attr("out_w", -1)
    scale = op.attr("scale", 0.0)
    if scale and (oh <= 0 or ow <= 0):
        oh = int(x.shape[2] * scale)
        ow = int(x.shape[3] * scale)
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), method="bilinear")
    return {"Out": out}


@register_op("pad", diff_inputs=("X",))
def pad(ctx, op, ins):
    x = ins["X"][0]
    p = op.attr("paddings")
    val = op.attr("pad_value", 0.0)
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pairs, constant_values=val)}


@register_op("pad2d", diff_inputs=("X",))
def pad2d(ctx, op, ins):
    x = ins["X"][0]
    p = op.attr("paddings", [0, 0, 0, 0])
    mode = op.attr("mode", "constant")
    val = op.attr("pad_value", 0.0)
    pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": jnp.pad(x, pairs, constant_values=val)}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, pairs, mode=jmode)}


@register_op("clip_by_norm", diff_inputs=("X",))
def clip_by_norm(ctx, op, ins):
    x = ins["X"][0]
    max_norm = op.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": (x.astype(jnp.float32) * scale).astype(x.dtype)}


@register_op("unfold", diff_inputs=("X",))
def unfold(ctx, op, ins):
    """im2col (reference operators/unfold_op.cc / math/im2col): NCHW ->
    (N, C*kh*kw, L)."""
    x = ins["X"][0]
    ks = op.attr("kernel_sizes")
    strides = op.attr("strides", [1, 1])
    if isinstance(strides, int):
        strides = [strides, strides]
    pads = op.attr("paddings", [0, 0])
    if isinstance(pads, int):
        pads = [pads, pads]
    dil = op.attr("dilations", [1, 1])
    if isinstance(dil, int):
        dil = [dil, dil]
    n, c, h, w = x.shape
    p1 = pads[1] if len(pads) > 1 else pads[0]
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=tuple(ks), window_strides=tuple(strides),
        padding=[(pads[0], pads[0]), (p1, p1)],
        rhs_dilation=tuple(dil),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    # patches: (N, C*kh*kw, OH, OW) -> (N, C*kh*kw, L)
    return {"Y": patches.reshape(n, patches.shape[1], -1)}
