"""Fake-quantization ops — reference operators/fake_quantize_op.{cc,h} and
fake_dequantize_op.cc, the kernels behind contrib/slim QAT.

Simulated INT-N quantization: quantize-dequantize in one op with a
straight-through estimator (custom_vjp identity) so gradients flow through
the rounding — the reference gets the same effect from its
fake_quantize_dequantize grad kernels. All math stays in float on the MXU;
nothing here blocks XLA fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


@jax.custom_vjp
def _ste(x, q):
    """Pass q forward, route the cotangent straight through to x."""
    return q


def _ste_fwd(x, q):
    return q, None


def _ste_bwd(_, ct):
    return (ct, None)


_ste.defvjp(_ste_fwd, _ste_bwd)


def quant_dequant(x, scale, bits):
    qrange = float((1 << (bits - 1)) - 1)
    scale = jnp.maximum(scale, 1e-9)
    clipped = jnp.clip(x, -scale, scale)
    q = jnp.round(clipped / scale * qrange) / qrange * scale
    return _ste(x, q)


def _quant_only(x, scale, bits):
    """round(clip(x,-s,s)/s * range) — quantized integer levels stored as
    float (fake_quantize_op.h ClipAndFakeQuantFunctor)."""
    qrange = float((1 << (bits - 1)) - 1)
    scale = jnp.maximum(scale, 1e-9)
    return jnp.round(jnp.clip(x, -scale, scale) / scale * qrange)


@register_op("fake_quantize_abs_max", grad=None)
def fake_quantize_abs_max(ctx, op, ins):
    """fake_quantize_op.cc:499 FakeQuantizeAbsMaxOp (EmptyGradOpMaker —
    QAT passes pair this with a dequantize op; no grad of its own)."""
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": _quant_only(x, scale, bits), "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_abs_max", grad=None)
def fake_channel_wise_quantize_abs_max(ctx, op, ins):
    """fake_quantize_op.cc:535 — per-output-channel (axis 0) scales."""
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    red = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    return {"Out": _quant_only(x, scale, bits), "OutScale": scale.reshape(-1)}


@register_op("fake_quantize_range_abs_max", grad=None)
def fake_quantize_range_abs_max(ctx, op, ins):
    """fake_quantize_op.cc:507 FakeQuantizeRangeAbsMaxOp: sliding-window
    abs-max scale. The reference's data-dependent "recompute window max only
    when the evicted entry was the max" (FindRangeAbsMaxFunctor) becomes a
    branch-free lax.select over the static window buffer — same result,
    XLA-friendly.
    """
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    window = int(op.attr("window_size", 10000))
    in_scale = ins["InScale"][0].reshape(())
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    if is_test:
        return {"Out": _quant_only(x, in_scale, bits),
                "OutScale": in_scale.reshape(1)}
    cur = jnp.max(jnp.abs(x))
    it = (ins["Iter"][0].reshape(()).astype(jnp.int32)
          if ins.get("Iter") else jnp.asarray(0, jnp.int32))
    # OutScales is an in-out window buffer (an output-only slot in the
    # reference op); read its current value from the environment
    scales_names = op.outputs.get("OutScales") or []
    if scales_names and scales_names[0] in ctx.env:
        scales = ctx.env[scales_names[0]]
    else:
        scales = jnp.zeros((window,), x.dtype)
    idx = jnp.mod(it, window)
    removed = scales[idx]
    scales = scales.at[idx].set(cur)
    # valid prefix of the ring buffer: min(it, window) entries (+ the fresh
    # write, which jnp.maximum(cur, ...) below always counts)
    size = jnp.minimum(it, window)
    mask = jnp.arange(window) < size
    window_max = jnp.max(jnp.where(mask, jnp.abs(scales), 0.0))
    last = in_scale
    recompute = jnp.abs(removed - last) < 1e-6
    scale = jnp.where(last < cur, cur,
                      jnp.where(recompute, jnp.maximum(window_max, cur), last))
    return {"Out": _quant_only(x, scale, bits),
            "OutScale": scale.reshape(1), "OutScales": scales}


@register_op("fake_quantize_moving_average_abs_max", grad=None)
def fake_quantize_moving_average_abs_max(ctx, op, ins):
    """fake_quantize_op.cc:515 — moving-average scale, quantize only."""
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = int(op.attr("bit_length", 8))
    rho = float(op.attr("moving_rate", 0.9))
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    if is_test:
        return {"Out": _quant_only(x, in_scale, bits),
                "OutScale": in_scale.reshape(1)}
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else in_scale
    state = ins["InState"][0].reshape(()) if ins.get("InState") else \
        jnp.asarray(1.0, jnp.float32)
    state_new = rho * state + 1.0
    accum_new = rho * accum + jnp.max(jnp.abs(x))
    scale = accum_new / state_new
    return {"Out": _quant_only(x, scale, bits),
            "OutScale": scale.reshape(1),
            "OutAccum": accum_new.reshape(1),
            "OutState": state_new.reshape(1)}


@register_op("moving_average_abs_max_scale", diff_inputs=("X",))
def moving_average_abs_max_scale(ctx, op, ins):
    """fake_quantize_op.cc:543 MovingAverageAbsMaxScaleOp — scale
    observation only: Out = X, OutScale tracks the moving-average abs-max
    (quantization_pass.py:1481 inserts it after quantizable outputs)."""
    x = ins["X"][0]
    rho = float(op.attr("moving_rate", 0.9))
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    if is_test:
        in_accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else \
            jnp.asarray(1.0, jnp.float32)
        in_state = ins["InState"][0].reshape(()) if ins.get("InState") else \
            jnp.asarray(1.0, jnp.float32)
        return {"Out": x, "OutScale": (in_accum / in_state).reshape(1)}
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else \
        jnp.asarray(0.0, jnp.float32)
    state = ins["InState"][0].reshape(()) if ins.get("InState") else \
        jnp.asarray(0.0, jnp.float32)
    state_new = rho * state + 1.0
    accum_new = rho * accum + jnp.max(jnp.abs(x))
    return {"Out": x, "OutScale": (accum_new / state_new).reshape(1),
            "OutAccum": accum_new.reshape(1),
            "OutState": state_new.reshape(1)}


@register_op("fake_dequantize_max_abs", diff_inputs=("X",))
def fake_dequantize_max_abs(ctx, op, ins):
    """fake_dequantize_op.cc:182 — Out = Scale * X / max_range."""
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = float(op.attr("max_range", 127.0))
    return {"Out": x * scale / max_range}


@register_op("fake_channel_wise_dequantize_max_abs", diff_inputs=("X",))
def fake_channel_wise_dequantize_max_abs(ctx, op, ins):
    """fake_dequantize_op.cc:191 ChannelDequantizeFunctor — one scale set
    (per-channel weights, axis 0) or two (weight scales per channel on axis
    1 + a whole-tensor activation scale)."""
    x = ins["X"][0]
    scales = ins["Scales"]
    bits = [int(b) for b in (op.attr("quant_bits", [8]) or [8])]
    max_range = 1.0
    for b in bits[:len(scales)]:
        max_range *= float((1 << (b - 1)) - 1)
    if len(scales) == 1:
        s = scales[0].reshape((-1,) + (1,) * (x.ndim - 1))
        return {"Out": x * s / max_range}
    s1 = scales[0].reshape((1, -1) + (1,) * (x.ndim - 2))
    s2 = scales[1].reshape(())
    return {"Out": x * s1 * s2 / max_range}


@register_op("fake_quantize_dequantize_abs_max", diff_inputs=("X",))
def fake_quantize_dequantize_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": quant_dequant(x, scale, bits),
            "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             diff_inputs=("X",))
def fake_channel_wise_quantize_dequantize_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    axis = int(op.attr("quant_axis", 0))
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    out = quant_dequant(x, scale, bits)
    return {"Out": out, "OutScale": scale.reshape(-1)}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             diff_inputs=("X",))
def fake_quantize_dequantize_moving_average_abs_max(ctx, op, ins):
    """Activation quantization with a moving-average range estimate
    (fake_quantize_op.cc FakeQuantOrWithDequantMovingAverageAbsMaxOp):
        state  = rho * state + 1
        accum  = rho * accum + max(|x|)
        scale  = accum / state
    At test time the stored InScale is used unchanged.
    """
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = int(op.attr("bit_length", 8))
    rho = float(op.attr("moving_rate", 0.9))
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    if is_test:
        return {"Out": quant_dequant(x, in_scale, bits),
                "OutScale": in_scale.reshape(1)}
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else in_scale
    state = ins["InState"][0].reshape(()) if ins.get("InState") else \
        jnp.asarray(1.0, jnp.float32)
    cur = jnp.max(jnp.abs(x))
    state_new = rho * state + 1.0
    accum_new = rho * accum + cur
    scale = accum_new / state_new
    return {"Out": quant_dequant(x, scale, bits),
            "OutScale": scale.reshape(1),
            "OutAccum": accum_new.reshape(1),
            "OutState": state_new.reshape(1)}
