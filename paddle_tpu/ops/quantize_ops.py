"""Fake-quantization ops — reference operators/fake_quantize_op.{cc,h} and
fake_dequantize_op.cc, the kernels behind contrib/slim QAT.

Simulated INT-N quantization: quantize-dequantize in one op with a
straight-through estimator (custom_vjp identity) so gradients flow through
the rounding — the reference gets the same effect from its
fake_quantize_dequantize grad kernels. All math stays in float on the MXU;
nothing here blocks XLA fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


@jax.custom_vjp
def _ste(x, q):
    """Pass q forward, route the cotangent straight through to x."""
    return q


def _ste_fwd(x, q):
    return q, None


def _ste_bwd(_, ct):
    return (ct, None)


_ste.defvjp(_ste_fwd, _ste_bwd)


def quant_dequant(x, scale, bits):
    qrange = float((1 << (bits - 1)) - 1)
    scale = jnp.maximum(scale, 1e-9)
    clipped = jnp.clip(x, -scale, scale)
    q = jnp.round(clipped / scale * qrange) / qrange * scale
    return _ste(x, q)


@register_op("fake_quantize_dequantize_abs_max", diff_inputs=("X",))
def fake_quantize_dequantize_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": quant_dequant(x, scale, bits),
            "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             diff_inputs=("X",))
def fake_channel_wise_quantize_dequantize_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bits = int(op.attr("bit_length", 8))
    axis = int(op.attr("quant_axis", 0))
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    out = quant_dequant(x, scale, bits)
    return {"Out": out, "OutScale": scale.reshape(-1)}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             diff_inputs=("X",))
def fake_quantize_dequantize_moving_average_abs_max(ctx, op, ins):
    """Activation quantization with a moving-average range estimate
    (fake_quantize_op.cc FakeQuantOrWithDequantMovingAverageAbsMaxOp):
        state  = rho * state + 1
        accum  = rho * accum + max(|x|)
        scale  = accum / state
    At test time the stored InScale is used unchanged.
    """
    x = ins["X"][0]
    in_scale = ins["InScale"][0].reshape(())
    bits = int(op.attr("bit_length", 8))
    rho = float(op.attr("moving_rate", 0.9))
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    if is_test:
        return {"Out": quant_dequant(x, in_scale, bits),
                "OutScale": in_scale.reshape(1)}
    accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else in_scale
    state = ins["InState"][0].reshape(()) if ins.get("InState") else \
        jnp.asarray(1.0, jnp.float32)
    cur = jnp.max(jnp.abs(x))
    state_new = rho * state + 1.0
    accum_new = rho * accum + cur
    scale = accum_new / state_new
    return {"Out": quant_dequant(x, scale, bits),
            "OutScale": scale.reshape(1),
            "OutAccum": accum_new.reshape(1),
            "OutState": state_new.reshape(1)}
