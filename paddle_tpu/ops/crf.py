"""CRF + CTC ops — the sequence-labeling losses the reference ships as
linear_chain_crf_op.{cc,h}, crf_decoding_op.h, and warpctc_op.cc (external
warp-ctc library).

Dense TPU formulation (batch, max_len, ...) + Length masks, all recursions
as lax.scan in log space — one compiled XLA While instead of the reference's
per-sequence CPU loops, differentiable end-to-end by jax.vjp (warpctc's
hand-written grad kernel becomes autodiff through the alpha recursion).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import int_index_dtype
from ..framework.registry import register_op

_I64 = int_index_dtype  # call per use: jax_enable_x64 may toggle after import

_NEG = -1e30


def crf_nll(emission, transition, label, length):
    """Negative log likelihood per sequence.

    emission [B,T,D]; transition [D+2,D] (row0 start, row1 end, 2+ pairwise);
    label [B,T] int; length [B]. Matches linear_chain_crf_op.h semantics
    (test_linear_chain_crf_op.py oracle).
    """
    B, T, D = emission.shape
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]                      # [D,D] trans[i,j]: i -> j
    e = emission.astype(jnp.float32)
    lab = label.astype(jnp.int32)
    L = length.astype(jnp.int32)

    # ---- partition function: alpha recursion in log space ----------------
    alpha0 = start[None, :] + e[:, 0]           # [B,D]

    def step(alpha, t):
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) \
            + e[:, t]
        live = (t < L)[:, None]
        return jnp.where(live, nxt, alpha), None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)   # [B]

    # ---- gold path score --------------------------------------------------
    t_idx = jnp.arange(T)[None, :]
    valid = t_idx < L[:, None]                   # [B,T]
    em_score = jnp.take_along_axis(e, lab[..., None], axis=2)[..., 0]
    em_score = jnp.where(valid, em_score, 0.0).sum(axis=1)
    pair = trans[lab[:, :-1], lab[:, 1:]]        # [B,T-1]
    pair = jnp.where(valid[:, 1:], pair, 0.0).sum(axis=1)
    last = jnp.take_along_axis(lab, (L - 1)[:, None], axis=1)[:, 0]
    score = em_score + pair + start[lab[:, 0]] + stop[last]
    return (logz - score)[:, None]               # [B,1] NLL


@register_op("linear_chain_crf", diff_inputs=("Emission", "Transition"))
def linear_chain_crf(ctx, op, ins):
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[..., 0]
    if "Length" in ins and ins["Length"]:
        length = ins["Length"][0].reshape(-1)
    else:
        length = jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)
    nll = crf_nll(emission, transition, label, length)
    # parity outputs (the reference exposes its normalized-exp intermediates)
    return {"LogLikelihood": nll,
            "EmissionExps": jnp.exp(emission - emission.max(-1, keepdims=True)),
            "TransitionExps": jnp.exp(transition),
            "Alpha": jnp.zeros_like(emission)}


def crf_viterbi(emission, transition, length):
    """Viterbi decode. Returns [B,T] int64 best path (0 past length)."""
    B, T, D = emission.shape
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]
    e = emission.astype(jnp.float32)
    L = length.astype(jnp.int32)

    v0 = start[None, :] + e[:, 0]                # [B,D]

    def fwd(v, t):
        scores = v[:, :, None] + trans[None]     # [B,D,D]
        best = scores.max(axis=1) + e[:, t]
        arg = scores.argmax(axis=1)              # [B,D] backpointer
        live = (t < L)[:, None]
        return jnp.where(live, best, v), jnp.where(live, arg, -1)

    v, bptrs = lax.scan(fwd, v0, jnp.arange(1, T))   # bptrs [T-1,B,D]
    final = v + stop[None, :]
    last_tag = final.argmax(axis=1)              # [B]

    def back(tag, bp):
        # bp [B,D]: best predecessor of each tag; -1 marks a dead (padded)
        # step, where the tag just propagates backwards unchanged
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return jnp.where(prev >= 0, prev, tag), tag

    tag0, path_rev = lax.scan(back, last_tag, bptrs[::-1])
    # path_rev holds tags for positions T-1 .. 1; tag0 is position 0
    path = jnp.concatenate([tag0[None], path_rev[::-1]], axis=0).T  # [B,T]
    t_idx = jnp.arange(T)[None, :]
    return jnp.where(t_idx < L[:, None], path, 0).astype(_I64())


@register_op("crf_decoding", grad=None)
def crf_decoding(ctx, op, ins):
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    if "Length" in ins and ins["Length"]:
        length = ins["Length"][0].reshape(-1)
    else:
        length = jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)
    path = crf_viterbi(emission, transition, length)
    if "Label" in ins and ins["Label"]:
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label[..., 0]
        t_idx = jnp.arange(path.shape[1])[None, :]
        valid = t_idx < length.astype(jnp.int32)[:, None]
        # crf_decoding_op.h: with Label, emit 1 where path==label (0 in pad)
        path = jnp.where(valid & (label.astype(_I64()) == path), 1, 0) \
            .astype(_I64())
    return {"ViterbiPath": path}


# ---------------------------------------------------------------------------
# CTC (warpctc_op.cc) — log-space alpha recursion, autodiff grads
# ---------------------------------------------------------------------------

def ctc_loss(log_probs, labels, logit_lens, label_lens, blank=0):
    """log_probs [B,T,C] (log-softmaxed); labels [B,Lmax] int; returns [B]
    negative log likelihood.
    """
    B, T, C = log_probs.shape
    Lmax = labels.shape[1]
    S = 2 * Lmax + 1
    lab = labels.astype(jnp.int32)
    llen = label_lens.astype(jnp.int32)
    tlen = logit_lens.astype(jnp.int32)

    # extended sequence l' = [blank, l1, blank, l2, ..., blank]
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    s_idx = jnp.arange(S)[None, :]
    s_valid = s_idx < (2 * llen + 1)[:, None]     # [B,S]
    # skip-transition allowed where l'[s] != blank and l'[s] != l'[s-2]
    can_skip = jnp.zeros((B, S), bool)
    can_skip = can_skip.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    def emit(t):
        # log P(l'[s] at time t): gather [B,S]
        return jnp.take_along_axis(log_probs[:, t], ext, axis=1)

    a0 = jnp.full((B, S), _NEG)
    a0 = a0.at[:, 0].set(log_probs[:, 0, blank])
    first_lab = jnp.take_along_axis(log_probs[:, 0], lab[:, :1], axis=1)[:, 0]
    a0 = a0.at[:, 1].set(jnp.where(llen > 0, first_lab, _NEG))
    a0 = jnp.where(s_valid, a0, _NEG)

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((B, 1), _NEG), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate([jnp.full((B, 2), _NEG), alpha[:, :-2]], 1)
        prev2 = jnp.where(can_skip, prev2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        nxt = jnp.where(s_valid, merged + emit(t), _NEG)
        live = (t < tlen)[:, None]
        return jnp.where(live, nxt, alpha), None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, T))
    end1 = jnp.take_along_axis(alpha, (2 * llen)[:, None], axis=1)[:, 0]
    end2_idx = jnp.clip(2 * llen - 1, 0, S - 1)
    end2 = jnp.take_along_axis(alpha, end2_idx[:, None], axis=1)[:, 0]
    end2 = jnp.where(llen > 0, end2, _NEG)
    return -jnp.logaddexp(end1, end2)


@register_op("warpctc", diff_inputs=("Logits",))
def warpctc(ctx, op, ins):
    """warpctc_op.cc in padding mode: Logits [B,T,C] raw activations
    (softmax applied internally, like warp-ctc), Label [B,Lmax],
    LogitsLength [B], LabelLength [B]."""
    logits = ins["Logits"][0]
    labels = ins["Label"][0]
    if labels.ndim == 3:
        labels = labels[..., 0]
    B, T, C = logits.shape
    tlen = (ins["LogitsLength"][0].reshape(-1)
            if "LogitsLength" in ins and ins["LogitsLength"]
            else jnp.full((B,), T, jnp.int32))
    llen = (ins["LabelLength"][0].reshape(-1)
            if "LabelLength" in ins and ins["LabelLength"]
            else jnp.full((B,), labels.shape[1], jnp.int32))
    blank = int(op.attr("blank", 0))
    if bool(op.attr("norm_by_times", False)):
        # warp-ctc normalizes only the GRADIENT by sequence length; the
        # Loss output stays unscaled (warpctc_op.h WarpCTCGradKernel)
        inv_t = (1.0 / jnp.maximum(tlen.astype(jnp.float32), 1.0)) \
            .reshape(-1, 1, 1)
        logits = _scale_grad(logits, inv_t)
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = ctc_loss(log_probs, labels, tlen, llen, blank=blank)
    return {"Loss": loss[:, None]}


@jax.custom_vjp
def _scale_grad(x, scale):
    return x


def _scale_grad_fwd(x, scale):
    return x, scale


def _scale_grad_bwd(scale, ct):
    return (ct * scale, None)


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)
