"""Math / elementwise / reduction op lowerings.

Capability parity with reference operators/elementwise/ (~8k LoC CUDA),
operators/reduce_ops/, and the dense-math portion of operators/*.cc — each
multi-hundred-line CUDA kernel family collapses to a jnp/lax expression that
XLA fuses and tiles onto the VPU/MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.core import dtype_to_jax, int_index_dtype
from ..framework.registry import (infer_cast, infer_identity, register_op)

# shared declared infer_shape for the shape-preserving families below —
# skips the per-append eval_shape trace and marks the op "declared" in
# tools/OP_DESC.spec's inference-coverage column
_INFER_X = infer_identity("X", "Out")

_I64 = int_index_dtype  # call per use: jax_enable_x64 may toggle after import

# ---------------------------------------------------------------------------
# Creation / fill ops (reference operators/fill_constant_op.cc etc.)
# ---------------------------------------------------------------------------


@register_op("fill_constant", grad=None)
def fill_constant(ctx, op, ins):
    shape = [int(s) for s in op.attr("shape", [])]
    dtype = dtype_to_jax(op.attr("dtype", "float32"))
    value = op.attr("value", 0.0)
    if "ShapeTensor" in ins and ins["ShapeTensor"]:
        shape = [int(x) for x in np.asarray(ins["ShapeTensor"][0])]
    # a NUMPY constant, not jnp: jit staging would turn a literal into a
    # tracer, and downstream consumers that need static values (tensor-array
    # indices, shape operands) could no longer concretize it.  jnp consumers
    # fold np arrays transparently.
    np_dtype = np.dtype(dtype) if dtype != jnp.bfloat16 else None
    if np_dtype is not None:
        return {"Out": np.full(shape, value, dtype=np_dtype)}
    return {"Out": jnp.full(shape, value, dtype=dtype)}


@register_op("fill_constant_batch_size_like", grad=None)
def fill_constant_batch_size_like(ctx, op, ins):
    """fill_constant_batch_size_like_op.cc: fill a constant tensor whose
    output_dim_idx dim is copied from the input's input_dim_idx dim."""
    x = ins["Input"][0]
    shape = [int(s) for s in op.attr("shape", [])]
    in_idx = int(op.attr("input_dim_idx", 0))
    out_idx = int(op.attr("output_dim_idx", 0))
    shape[out_idx] = x.shape[in_idx]
    dtype = dtype_to_jax(op.attr("dtype", "float32"))
    return {"Out": jnp.full(shape, op.attr("value", 0.0), dtype=dtype)}


@register_op("fill_zeros_like", grad=None)
def fill_zeros_like(ctx, op, ins):
    return {"Out": jnp.zeros_like(ins["X"][0])}


@register_op("fill_any_like", grad=None)
def fill_any_like(ctx, op, ins):
    dtype = op.attr("dtype")
    x = ins["X"][0]
    dt = dtype_to_jax(dtype) if dtype is not None else x.dtype
    return {"Out": jnp.full_like(x, op.attr("value", 0.0), dtype=dt)}


@register_op("assign")
def assign(ctx, op, ins):
    return {"Out": ins["X"][0]}


@register_op("shape", grad=None)
def shape_op(ctx, op, ins):
    return {"Out": jnp.asarray(ins["Input"][0].shape, dtype=jnp.int32)}


# ---------------------------------------------------------------------------
# Elementwise binary ops with axis broadcasting
# (reference operators/elementwise/elementwise_op_function.h broadcast rules:
#  Y's shape aligns to X at `axis`; -1 means numpy-style ranks-aligned-right)
# ---------------------------------------------------------------------------


def _broadcast_y(x, y, axis):
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    if y.ndim > x.ndim:
        # Y of higher rank only broadcasts if its extra leading dims are 1
        # (e.g. scalar loss * [1]-shaped loss_scaling); squeeze them away.
        extra = y.ndim - x.ndim
        if any(d != 1 for d in y.shape[:extra]):
            raise ValueError(
                f"elementwise broadcast: Y rank {y.ndim} > X rank {x.ndim} "
                f"with non-unit leading dims {y.shape}")
        return jnp.reshape(y, y.shape[extra:])
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    # insert trailing singleton dims so y aligns at `axis`
    new_shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        new_shape[axis + i] = d
    return jnp.reshape(y, new_shape)


def _ew(fn):
    def lower(ctx, op, ins):
        x, y = ins["X"][0], ins["Y"][0]
        y = _broadcast_y(x, y, op.attr("axis", -1))
        return {"Out": fn(x, y)}

    return lower


# paddle elementwise broadcasts Y INTO X's shape, so Out always carries
# X's metadata — infer_identity is exact for the whole family
register_op("elementwise_add", infer_shape=_INFER_X)(_ew(jnp.add))
register_op("elementwise_sub", infer_shape=_INFER_X)(_ew(jnp.subtract))
register_op("elementwise_mul", infer_shape=_INFER_X)(_ew(jnp.multiply))
register_op("elementwise_div", infer_shape=_INFER_X)(_ew(jnp.divide))
register_op("elementwise_min", infer_shape=_INFER_X)(_ew(jnp.minimum))
register_op("elementwise_max", infer_shape=_INFER_X)(_ew(jnp.maximum))
register_op("elementwise_pow", infer_shape=_INFER_X)(_ew(jnp.power))
register_op("elementwise_mod", grad=None, infer_shape=_INFER_X)(_ew(jnp.mod))
register_op("elementwise_floordiv", grad=None,
            infer_shape=_INFER_X)(_ew(jnp.floor_divide))


@register_op("scale", infer_shape=_INFER_X)
def scale(ctx, op, ins):
    x = ins["X"][0]
    s = op.attr("scale", 1.0)
    if "ScaleTensor" in ins and ins["ScaleTensor"]:
        s = ins["ScaleTensor"][0]
    bias = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        return {"Out": x * s + jnp.asarray(bias, x.dtype)}
    return {"Out": (x + jnp.asarray(bias, x.dtype)) * s}


@register_op("sum", infer_shape=_INFER_X)
def sum_op(ctx, op, ins):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("clip", infer_shape=_INFER_X)
def clip(ctx, op, ins):
    return {"Out": jnp.clip(ins["X"][0], op.attr("min"), op.attr("max"))}


@register_op("cast", diff_inputs=("X",), infer_shape=infer_cast)
def cast(ctx, op, ins):
    return {"Out": ins["X"][0].astype(dtype_to_jax(op.attr("out_dtype")))}


# ---------------------------------------------------------------------------
# Unary math (reference operators/activation_op.* one templated file)
# ---------------------------------------------------------------------------

_UNARY = {
    "exp": jnp.exp,
    "log": jnp.log,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "round": jnp.round,
    "reciprocal": lambda x: 1.0 / x,
    "square": jnp.square,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "sign": jnp.sign,
    "erf": jax.scipy.special.erf,
    "logsigmoid": jax.nn.log_sigmoid,
    "softsign": jax.nn.soft_sign,
}

for _name, _fn in _UNARY.items():
    register_op(_name, infer_shape=_INFER_X)(
        (lambda fn: lambda ctx, op, ins: {"Out": fn(ins["X"][0])})(_fn)
    )


@register_op("pow")
def pow_op(ctx, op, ins):
    factor = op.attr("factor", 1.0)
    if "FactorTensor" in ins and ins["FactorTensor"]:
        factor = ins["FactorTensor"][0]
    return {"Out": jnp.power(ins["X"][0], factor)}


# ---------------------------------------------------------------------------
# Matmul family — the MXU path. bf16-friendly, batched.
# (reference operators/matmul_op.cc, mul_op.cc, bmm, dot)
# ---------------------------------------------------------------------------


@register_op("matmul")
def matmul(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    tx, ty = op.attr("transpose_X", False), op.attr("transpose_Y", False)
    alpha = op.attr("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x.dtype))
    out = out.astype(x.dtype)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


def _acc_type(dtype):
    # accumulate matmuls in f32 when inputs are low-precision (MXU native)
    if dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return dtype


@register_op("matmul_v2")
def matmul_v2(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    if op.attr("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if op.attr("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x.dtype)).astype(x.dtype)
    return {"Out": out}


@register_op("mul")
def mul(ctx, op, ins):
    """reference mul_op: flattens X to 2D at x_num_col_dims, Y at y_num_col_dims."""
    x, y = ins["X"][0], ins["Y"][0]
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xnc])), int(np.prod(xs[xnc:]))))
    y2 = y.reshape((int(np.prod(ys[:ync])), int(np.prod(ys[ync:]))))
    out = jnp.matmul(x2, y2, preferred_element_type=_acc_type(x2.dtype)).astype(x.dtype)
    return {"Out": out.reshape(xs[:xnc] + ys[ync:])}


@register_op("bmm")
def bmm(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.matmul(x, y, preferred_element_type=_acc_type(x.dtype)).astype(x.dtype)}


@register_op("dot")
def dot(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=True)}


# ---------------------------------------------------------------------------
# Reductions (reference operators/reduce_ops/)
# ---------------------------------------------------------------------------


def _reduce(fn, differentiable=True):
    def lower(ctx, op, ins):
        x = ins["X"][0]
        dims = op.attr("dim", [0])
        keep = op.attr("keep_dim", False)
        if op.attr("reduce_all", False) or dims is None or len(dims) == 0:
            axes = tuple(range(x.ndim))
        else:
            axes = tuple(d % max(x.ndim, 1) for d in dims)
        return {"Out": fn(x, axis=axes, keepdims=keep)}

    return lower


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))
register_op("reduce_all", grad=None)(_reduce(jnp.all))
register_op("reduce_any", grad=None)(_reduce(jnp.any))


@register_op("mean")
def mean(ctx, op, ins):
    return {"Out": jnp.mean(ins["X"][0])}


@register_op("logsumexp")
def logsumexp(ctx, op, ins):
    x = ins["X"][0]
    dims = op.attr("dim", None) or op.attr("axis", None)
    keep = op.attr("keep_dim", False) or op.attr("keepdim", False)
    axes = tuple(dims) if dims else None
    return {"Out": jax.scipy.special.logsumexp(x, axis=axes, keepdims=keep)}


# ---------------------------------------------------------------------------
# Comparison / logical (reference operators/controlflow/compare_op.cc)
# ---------------------------------------------------------------------------

_CMP = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
}
for _name, _fn in _CMP.items():
    register_op(_name, grad=None)(
        (lambda fn: lambda ctx, op, ins: {"Out": fn(ins["X"][0], ins["Y"][0])})(_fn)
    )

register_op("logical_and", grad=None)(
    lambda ctx, op, ins: {"Out": jnp.logical_and(ins["X"][0], ins["Y"][0])}
)
register_op("logical_or", grad=None)(
    lambda ctx, op, ins: {"Out": jnp.logical_or(ins["X"][0], ins["Y"][0])}
)
register_op("logical_xor", grad=None)(
    lambda ctx, op, ins: {"Out": jnp.logical_xor(ins["X"][0], ins["Y"][0])}
)
register_op("logical_not", grad=None)(
    lambda ctx, op, ins: {"Out": jnp.logical_not(ins["X"][0])}
)


@register_op("isfinite", grad=None)
def isfinite(ctx, op, ins):
    # reference isfinite_op reduces to a single bool over the whole tensor
    return {"Out": jnp.all(jnp.isfinite(ins["X"][0]))[None]}


@register_op("isfinite_v2", grad=None)
def isfinite_v2(ctx, op, ins):
    return {"Out": jnp.isfinite(ins["X"][0])}


@register_op("isnan_v2", grad=None)
def isnan_v2(ctx, op, ins):
    return {"Out": jnp.isnan(ins["X"][0])}


@register_op("isinf_v2", grad=None)
def isinf_v2(ctx, op, ins):
    return {"Out": jnp.isinf(ins["X"][0])}


# ---------------------------------------------------------------------------
# argmax/argmin/argsort/topk (reference arg_min_max_op, argsort_op, top_k_op)
# ---------------------------------------------------------------------------


@register_op("arg_max", grad=None)
def arg_max(ctx, op, ins):
    axis = op.attr("axis", -1)
    return {"Out": jnp.argmax(ins["X"][0], axis=axis).astype(_I64())}


@register_op("arg_min", grad=None)
def arg_min(ctx, op, ins):
    axis = op.attr("axis", -1)
    return {"Out": jnp.argmin(ins["X"][0], axis=axis).astype(_I64())}


@register_op("argsort", grad=None)
def argsort(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", -1)
    desc = op.attr("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(_I64())}


@register_op("top_k", diff_inputs=())
def top_k(ctx, op, ins):
    x = ins["X"][0]
    k = op.attr("k", 1)
    if "K" in ins and ins["K"]:
        k = int(np.asarray(ins["K"][0]))
    vals, idx = lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(_I64())}


@register_op("top_k_v2", diff_inputs=())
def top_k_v2(ctx, op, ins):
    x = ins["X"][0]
    k = op.attr("k", 1)
    if op.attr("largest", True):
        vals, idx = lax.top_k(x, k)
    else:
        vals, idx = lax.top_k(-x, k)
        vals = -vals
    return {"Out": vals, "Indices": idx.astype(_I64())}


@register_op("accuracy", grad=None)
def accuracy(ctx, op, ins):
    """reference operators/metrics/accuracy_op: Out from topk Indices vs Label."""
    idx = ins["Indices"][0]
    label = ins["Label"][0]
    if label.ndim == idx.ndim - 1:
        label = label[..., None]
    correct = jnp.any(idx == label, axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = correct.shape[0] if correct.ndim else 1
    acc = num_correct.astype(jnp.float32) / float(np.prod(correct.shape))
    return {
        "Accuracy": acc[None],
        "Correct": num_correct[None],
        "Total": jnp.asarray([int(np.prod(correct.shape))], dtype=jnp.int32),
    }


@register_op("increment", grad=None)
def increment(ctx, op, ins):
    x = ins["X"][0]
    return {"Out": x + jnp.asarray(op.attr("step", 1.0), dtype=x.dtype)}
