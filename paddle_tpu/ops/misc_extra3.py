"""Long-tail op batch 6 — the last implementable reference names:
lod_reset, split_byref, int8 quantize family, blocking queues, the fleet
sparse-table host API (pull_sparse/push_sparse + v2), recv_save, and the
cross_entropy_grad2 name alias.

What remains absent after this batch is absent BY DESIGN: fusion_* /
fused_* (XLA fusion), mkldnn/tensorrt/lite engines, nccl/gen_nccl_id
(XLA collectives), run_program
(dygraph partial programs stage through jax.jit directly), pyramid_hash/var_conv_2d (niche fused CPU kernels whose
capability the generic op set covers; rank_attention/tree_conv/
attention_lstm gained real lowerings after this batch).
"""
from __future__ import annotations

import queue as queue_mod
from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..framework.executor import register_host_op
from ..framework.registry import get_op_spec, register_op


@register_op("lod_reset", diff_inputs=("X",))
def lod_reset(ctx, op, ins):
    """operators/lod_reset_op.cc: values pass through; the sequence
    partition is replaced. Padded convention: the new partition is the
    Y/TargetLod length vector."""
    x = ins["X"][0]
    outs = {"Out": x}
    if ins.get("Y"):
        outs["Length"] = ins["Y"][0]
    elif op.attr("target_lod", None):
        lod = [int(v) for v in op.attr("target_lod")]
        outs["Length"] = jnp.asarray(np.diff(np.asarray(lod)), jnp.int32)
    return outs


@register_op("split_byref", diff_inputs=("X",))
def split_byref(ctx, op, ins):
    """operators/split_byref_op.cc: split without copy — XLA views are
    already zero-copy; semantics == split along axis 0 by sections."""
    x = ins["X"][0]
    n_out = len(op.outputs.get("Out", []))
    sections = op.attr("sections", None)
    if not sections:
        sections = [x.shape[0] // n_out] * n_out
    outs, off = [], 0
    for s in sections:
        outs.append(x[off:off + s])
        off += s
    return {"Out": outs}


# ---------------------------------------------------------------------------
# int8 quantize family (operators/quantize_op.cc etc. — mkldnn kernels in
# the reference; the affine math is the portable part)
# ---------------------------------------------------------------------------


@register_op("quantize", grad=None)
def quantize(ctx, op, ins):
    scale = float(op.attr("Scale", 1.0))
    shift = float(op.attr("Shift", 0.0))
    x = ins["Input"][0]
    q = jnp.round(x.astype(jnp.float32) * scale + shift)
    if op.attr("is_negative_input", True) and shift == 0.0:
        return {"Output": jnp.clip(q, -128, 127).astype(jnp.int8)}
    return {"Output": jnp.clip(q, 0, 255).astype(jnp.uint8)}


@register_op("dequantize", grad=None)
def dequantize(ctx, op, ins):
    scale = float(op.attr("Scale", 1.0))
    shift = float(op.attr("Shift", 0.0))
    x = ins["Input"][0].astype(jnp.float32)
    return {"Output": (x - shift) / scale}


@register_op("requantize", grad=None)
def requantize(ctx, op, ins):
    s_in = float(op.attr("Scale_in", 1.0))
    s_out = float(op.attr("Scale_out", 1.0))
    x = ins["Input"][0].astype(jnp.float32)
    q = jnp.round(x * (s_out / s_in))
    return {"Output": jnp.clip(q, -128, 127).astype(jnp.int8)}


# ---------------------------------------------------------------------------
# blocking queues (operators/controlflow/queue_generator_op /
# enqueue_op / dequeue_op — pipeline section plumbing)
# ---------------------------------------------------------------------------

_QUEUES: Dict[str, "queue_mod.Queue"] = {}


@register_host_op("queue_generator")
def queue_generator(scope, op, exe):
    for name in op.attr("names", []):
        _QUEUES.setdefault(name, queue_mod.Queue(
            maxsize=int(op.attr("capacity", 64))))


@register_host_op("enqueue")
def enqueue(scope, op, exe):
    qname = op.attr("queue_name")
    _QUEUES.setdefault(qname, queue_mod.Queue())
    v = scope.find_var(op.input("X")[0])
    _QUEUES[qname].put(np.asarray(v))


@register_host_op("dequeue")
def dequeue(scope, op, exe):
    qname = op.attr("queue_name")
    _QUEUES.setdefault(qname, queue_mod.Queue())
    val = _QUEUES[qname].get()
    scope.set_var(op.output("Out")[0], jnp.asarray(val))


# ---------------------------------------------------------------------------
# fleet sparse-table host API (operators/pull_sparse_op.cc / v2 — the
# FleetWrapper sparse path; here over the same PSClient as
# distributed_lookup_table)
# ---------------------------------------------------------------------------


def _ps_client(op):
    from ..distributed import PSClient

    return PSClient.instance(int(op.attr("trainer_id", 0)))


@register_host_op("pull_sparse")
def pull_sparse(scope, op, exe):
    eps = op.attr("epmap", [])
    tables = op.attr("table_names", []) or [op.attr("TableId", 0)]
    client = _ps_client(op)
    for i, (ids_name, out_name) in enumerate(zip(op.input("Ids"),
                                                 op.output("Out"))):
        ids = np.asarray(scope.find_var(ids_name))
        table = str(tables[min(i, len(tables) - 1)])
        rows = client.pull_sparse(eps[0], table,
                                  ids.reshape(-1).astype(np.uint64))
        scope.set_var(out_name,
                      jnp.asarray(rows.reshape(*ids.shape[:-1], -1)
                                  if ids.ndim > 1 and ids.shape[-1] == 1
                                  else rows.reshape(len(ids.reshape(-1)),
                                                    -1)))


@register_host_op("pull_sparse_v2")
def pull_sparse_v2(scope, op, exe):
    pull_sparse(scope, op, exe)


@register_host_op("push_sparse")
def push_sparse(scope, op, exe):
    eps = op.attr("epmap", [])
    tables = op.attr("table_names", []) or [op.attr("TableId", 0)]
    client = _ps_client(op)
    grads = op.input("Out@GRAD") if "Out@GRAD" in op.inputs \
        else op.input("Grad")
    for i, (ids_name, g_name) in enumerate(zip(op.input("Ids"), grads)):
        ids = np.asarray(scope.find_var(ids_name)).reshape(-1)
        g = np.asarray(scope.find_var(g_name))
        table = str(tables[min(i, len(tables) - 1)])
        client.push_sparse(eps[0], table, ids.astype(np.uint64),
                           g.reshape(ids.size, -1))


@register_host_op("push_sparse_v2")
def push_sparse_v2(scope, op, exe):
    push_sparse(scope, op, exe)


@register_host_op("recv_save")
def recv_save(scope, op, exe):
    """operators/distributed_ops/recv_save_op.cc: pull a remote param and
    persist it in the reference tensor-stream format (fleet checkpoint of
    pserver-resident params without routing through a trainer var)."""
    import os

    from ..framework import paddle_pb

    eps = op.attr("epmap")
    param = op.attr("param") or op.attr("varname")
    path = op.attr("file_path")
    client = _ps_client(op)
    value = client.pull(eps[0], param)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(paddle_pb.tensor_to_stream(np.asarray(value)))


# cross_entropy2's grad op registers under the reference's historical name
# (cross_entropy_grad2, cross_entropy_op.cc) as well as the generic
# <type>_grad the backward pass emits.
def _register_ce_grad2_alias():
    from ..framework.registry import _OPS, _generic_grad_spec

    spec = _generic_grad_spec("cross_entropy2_grad")
    _OPS["cross_entropy_grad2"] = spec


_register_ce_grad2_alias()
