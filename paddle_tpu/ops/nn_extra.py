"""Long-tail NN/vision/loss operators, batch 2 — closing the remaining
top-level operators/*.cc families: affine/grid/interp transforms, indexed
pooling + unpool, transposed 3d/depthwise convs, RNN unit steps, niche
losses, partial concat/sum, batched fc, spectral norm, cholesky.

Every lowering is a direct jnp/lax expression of the reference kernel's
math (cited per op); grads come from the generic vjp machinery.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.core import dtype_to_jax, int_index_dtype
from ..framework.registry import register_op

_I64 = int_index_dtype  # call per use: jax_enable_x64 may toggle after import


# ---------------------------------------------------------------------------
# channel/grid transforms
# ---------------------------------------------------------------------------


@register_op("affine_channel", diff_inputs=("X", "Scale", "Bias"))
def affine_channel(ctx, op, ins):
    """operators/affine_channel_op.cc: Y = X * scale[C] + bias[C]."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    layout = op.attr("data_layout", "NCHW")
    shape = ((1, -1) + (1,) * (x.ndim - 2)) if layout == "NCHW" \
        else ((1,) * (x.ndim - 1) + (-1,))
    return {"Out": x * scale.reshape(shape) + bias.reshape(shape)}


@register_op("affine_grid", diff_inputs=("Theta",))
def affine_grid(ctx, op, ins):
    """operators/affine_grid_op.cc: theta [N,2,3] -> sampling grid
    [N,H,W,2] over normalized [-1,1] coords (align_corners=True extents)."""
    theta = ins["Theta"][0]
    if ins.get("OutputShape"):
        oshape = [int(v) for v in np.asarray(ins["OutputShape"][0])]
    else:
        oshape = [int(v) for v in op.attr("output_shape")]
    N, _, H, W = oshape
    align = bool(op.attr("align_corners", True))
    if align:
        xs = jnp.linspace(-1.0, 1.0, W)
        ys = jnp.linspace(-1.0, 1.0, H)
    else:
        xs = (jnp.arange(W) * 2 + 1) / W - 1
        ys = (jnp.arange(H) * 2 + 1) / H - 1
    gx, gy = jnp.meshgrid(xs, ys)                    # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)        # [H, W, 3]
    grid = jnp.einsum("hwk,nck->nhwc", base.astype(theta.dtype), theta)
    return {"Output": grid}                          # [N, H, W, 2]


@register_op("multiplex", diff_inputs=("X",))
def multiplex(ctx, op, ins):
    """operators/multiplex_op.cc: out[b] = X[ids[b]][b]."""
    xs = jnp.stack(ins["X"], axis=0)                 # [K, B, ...]
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    b = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, b]}


# ---------------------------------------------------------------------------
# indexed pooling / unpool
# ---------------------------------------------------------------------------


def _max_pool_with_index(x, ksize, strides, paddings, adaptive=False):
    N, C = x.shape[:2]
    spatial = x.shape[2:]
    nd = len(spatial)
    if adaptive:
        raise NotImplementedError("adaptive max_pool_with_index")
    # window extraction via reduce_window over value and flat-position
    pos = jnp.arange(int(np.prod(spatial))).reshape((1, 1) + spatial)
    pos = jnp.broadcast_to(pos, x.shape)
    neg_inf = -jnp.inf

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    init = (jnp.asarray(neg_inf, jnp.float32), jnp.asarray(-1, jnp.int32))
    window = (1, 1) + tuple(ksize)
    stride = (1, 1) + tuple(strides)
    pad = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    vals, idxs = lax.reduce_window(
        (x.astype(jnp.float32), pos.astype(jnp.int32)), init, sel,
        window, stride, pad)
    return vals.astype(x.dtype), idxs


@register_op("max_pool2d_with_index", diff_inputs=("X",))
def max_pool2d_with_index(ctx, op, ins):
    """operators/pool_with_index_op.cc: max pool emitting the flat H*W
    argmax per output cell (the mask unpool consumes)."""
    x = ins["X"][0]
    out, mask = _max_pool_with_index(
        x, op.attr("ksize"), op.attr("strides", [1, 1]),
        op.attr("paddings", [0, 0]))
    return {"Out": out, "Mask": mask.astype(_I64())}


@register_op("max_pool3d_with_index", diff_inputs=("X",))
def max_pool3d_with_index(ctx, op, ins):
    x = ins["X"][0]
    out, mask = _max_pool_with_index(
        x, op.attr("ksize"), op.attr("strides", [1, 1, 1]),
        op.attr("paddings", [0, 0, 0]))
    return {"Out": out, "Mask": mask.astype(_I64())}


@register_op("unpool", diff_inputs=("X",))
def unpool(ctx, op, ins):
    """operators/unpool_op.cc (unpooltype=max): scatter pooled values back
    to the argmax positions recorded by max_pool2d_with_index."""
    x = ins["X"][0]                                  # [N, C, h, w]
    idx = ins["Indices"][0].astype(jnp.int32)        # [N, C, h, w] flat HW
    oh = int(op.attr("unpooled_height", 0))
    ow = int(op.attr("unpooled_width", 0))
    if not oh:
        oh, ow = x.shape[2] * 2, x.shape[3] * 2
    N, C = x.shape[:2]
    flat = jnp.zeros((N, C, oh * ow), x.dtype)
    flat = flat.at[
        jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None],
        idx.reshape(N, C, -1)].add(x.reshape(N, C, -1))
    return {"Out": flat.reshape(N, C, oh, ow)}


# ---------------------------------------------------------------------------
# interpolation
# ---------------------------------------------------------------------------


def _interp_size(op, ins, spatial_in, nd):
    if ins.get("OutSize"):
        sz = [int(v) for v in np.asarray(ins["OutSize"][0])]
        return sz
    scale = op.attr("scale", 0.0)
    if scale and scale > 0:
        return [int(s * scale) for s in spatial_in]
    names2 = {1: ["out_w"], 2: ["out_h", "out_w"],
              3: ["out_d", "out_h", "out_w"]}[nd]
    return [int(op.attr(n)) for n in names2]


def _resize_linear_nd(x, out_sz, align_corners, align_mode=1):
    """jax.image-free separable linear resize matching the reference's
    align_corners / align_mode=0 half-pixel conventions. x: [N, C, *S]."""
    nd = x.ndim - 2
    out = x
    for d in range(nd):
        in_s = out.shape[2 + d]
        o = out_sz[d]
        if align_corners:
            pts = jnp.linspace(0.0, in_s - 1.0, o)
        elif align_mode == 0:  # half-pixel
            pts = jnp.clip((jnp.arange(o) + 0.5) * in_s / o - 0.5, 0,
                           in_s - 1)
        else:
            pts = jnp.clip(jnp.arange(o) * in_s / o, 0, in_s - 1)
        lo = jnp.floor(pts).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, in_s - 1)
        w = (pts - lo).astype(out.dtype)
        ax = 2 + d
        a = jnp.take(out, lo, axis=ax)
        b = jnp.take(out, hi, axis=ax)
        shape = [1] * out.ndim
        shape[ax] = o
        w = w.reshape(shape)
        out = a * (1 - w) + b * w
    return out


@register_op("linear_interp", diff_inputs=("X",))
def linear_interp(ctx, op, ins):
    """operators/interpolate_op.cc linear mode on [N, C, W]."""
    x = ins["X"][0]
    sz = _interp_size(op, ins, x.shape[2:], 1)
    return {"Out": _resize_linear_nd(
        x, sz, bool(op.attr("align_corners", True)),
        int(op.attr("align_mode", 1)))}


@register_op("trilinear_interp", diff_inputs=("X",))
def trilinear_interp(ctx, op, ins):
    """operators/interpolate_op.cc trilinear mode on [N, C, D, H, W]."""
    x = ins["X"][0]
    sz = _interp_size(op, ins, x.shape[2:], 3)
    return {"Out": _resize_linear_nd(
        x, sz, bool(op.attr("align_corners", True)),
        int(op.attr("align_mode", 1)))}


def _cubic_weight(t, a=-0.75):
    at = jnp.abs(t)
    w1 = (a + 2) * at ** 3 - (a + 3) * at ** 2 + 1
    w2 = a * at ** 3 - 5 * a * at ** 2 + 8 * a * at - 4 * a
    return jnp.where(at <= 1, w1, jnp.where(at < 2, w2, 0.0))


@register_op("bicubic_interp", diff_inputs=("X",))
def bicubic_interp(ctx, op, ins):
    """operators/interpolate_op.cc bicubic (Keys a=-0.75) on [N, C, H, W]."""
    x = ins["X"][0]
    oh, ow = _interp_size(op, ins, x.shape[2:], 2)
    align = bool(op.attr("align_corners", True))
    out = x
    for d, o in ((0, oh), (1, ow)):
        in_s = out.shape[2 + d]
        if align and o > 1:
            pts = jnp.linspace(0.0, in_s - 1.0, o)
        else:
            pts = (jnp.arange(o) + 0.5) * in_s / o - 0.5
        base = jnp.floor(pts)
        frac = pts - base
        acc = None
        for k in range(-1, 3):
            idx = jnp.clip(base.astype(jnp.int32) + k, 0, in_s - 1)
            w = _cubic_weight(frac - k).astype(out.dtype)
            shape = [1] * out.ndim
            shape[2 + d] = o
            term = jnp.take(out, idx, axis=2 + d) * w.reshape(shape)
            acc = term if acc is None else acc + term
        out = acc
    return {"Out": out}


# ---------------------------------------------------------------------------
# transposed convs
# ---------------------------------------------------------------------------


@register_op("conv3d_transpose", diff_inputs=("Input", "Filter"))
def conv3d_transpose(ctx, op, ins):
    """operators/conv_transpose_op.cc, 3-D."""
    x, w = ins["Input"][0], ins["Filter"][0]
    from .nn import conv_transpose_nd
    return {"Output": conv_transpose_nd(
        x, w, tuple(op.attr("strides", [1, 1, 1])),
        tuple(op.attr("paddings", [0, 0, 0])),
        tuple(op.attr("dilations", [1, 1, 1])),
        int(op.attr("groups", 1) or 1), nd=3)}


@register_op("depthwise_conv2d_transpose", diff_inputs=("Input", "Filter"))
def depthwise_conv2d_transpose(ctx, op, ins):
    x, w = ins["Input"][0], ins["Filter"][0]
    from .nn import conv_transpose_nd

    out = conv_transpose_nd(
        x, w, tuple(op.attr("strides", [1, 1])),
        tuple(op.attr("paddings", [0, 0])),
        tuple(op.attr("dilations", [1, 1])), x.shape[1], nd=2)
    return {"Output": out}


# ---------------------------------------------------------------------------
# RNN unit steps
# ---------------------------------------------------------------------------


@register_op("gru_unit", diff_inputs=("Input", "HiddenPrev", "Weight", "Bias"))
def gru_unit(ctx, op, ins):
    """operators/gru_unit_op.h: one GRU step. Input [B, 3D] (x projection),
    Weight [D, 3D] (cols [0,2D) gates u,r; [2D,3D) candidate), gate layout
    [u, r, c]. h = u*(c - h_p) + h_p (origin_mode: c + u*(h_p - c))."""
    xg = ins["Input"][0]
    h_p = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    D = h_p.shape[1]
    g = xg
    if ins.get("Bias"):
        g = g + ins["Bias"][0].reshape(1, -1)
    acts = {0: lambda v: v, 1: jax.nn.sigmoid, 2: jnp.tanh, 3: jax.nn.relu}
    gate_act = acts[int(op.attr("gate_activation", 1))]
    cand_act = acts[int(op.attr("activation", 2))]
    ur = g[:, :2 * D] + h_p @ w[:, :2 * D]
    u = gate_act(ur[:, :D])
    r = gate_act(ur[:, D:])
    r_h_p = r * h_p
    c = cand_act(g[:, 2 * D:] + r_h_p @ w[:, 2 * D:])
    if op.attr("origin_mode", False):
        h = c + u * (h_p - c)
    else:
        h = u * (c - h_p) + h_p
    gates = jnp.concatenate([u, r, c], axis=1)
    return {"Gate": gates, "ResetHiddenPrev": r_h_p, "Hidden": h}


@register_op("lstm_unit", diff_inputs=("X", "C_prev"))
def lstm_unit(ctx, op, ins):
    """operators/lstm_unit_op.h: X [B, 4D] split (i, f, o, g);
    c = sigmoid(f + forget_bias)*c_prev + sigmoid(i)*tanh(g);
    h = sigmoid(o)*tanh(c)."""
    x = ins["X"][0]
    c_prev = ins["C_prev"][0]
    fb = float(op.attr("forget_bias", 0.0))
    D = c_prev.shape[1]
    i = jax.nn.sigmoid(x[:, :D])
    f = jax.nn.sigmoid(x[:, D:2 * D] + fb)
    o = jax.nn.sigmoid(x[:, 2 * D:3 * D])
    g = jnp.tanh(x[:, 3 * D:])
    c = f * c_prev + i * g
    return {"C": c, "H": o * jnp.tanh(c)}


@register_op("lstmp", diff_inputs=("Input", "Weight", "ProjWeight", "Bias",
                                   "H0", "C0"))
def lstmp(ctx, op, ins):
    """operators/lstmp_op.cc: LSTM with recurrent projection. Padded form:
    Input [B, T, 4D] (pre-projected x), Weight [P, 4D] recurrent weights on
    the projected state r, ProjWeight [D, P]. Gate layout (i, f, c, o) per
    reference lstm compute; act attrs sigmoid/tanh defaults."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    wp = ins["ProjWeight"][0]
    D = wp.shape[0]
    P = wp.shape[1]
    B, T = x.shape[0], x.shape[1]
    bias = ins["Bias"][0].reshape(1, -1) if ins.get("Bias") else 0.0
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, P), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, D), x.dtype)

    def step(carry, xt):
        r_p, c_p = carry
        g = xt + r_p @ w + bias
        i = jax.nn.sigmoid(g[:, :D])
        f = jax.nn.sigmoid(g[:, D:2 * D])
        ct = jnp.tanh(g[:, 2 * D:3 * D])
        o = jax.nn.sigmoid(g[:, 3 * D:])
        c = f * c_p + i * ct
        h = o * jnp.tanh(c)
        r = jnp.tanh(h @ wp) if op.attr("proj_clip", 0.0) == 0.0 \
            else jnp.clip(jnp.tanh(h @ wp),
                          -op.attr("proj_clip"), op.attr("proj_clip"))
        return (r, c), (r, h, c)

    (_, _), (rs, hs, cs) = lax.scan(step, (h0, c0),
                                    jnp.moveaxis(x, 1, 0))
    proj = jnp.moveaxis(rs, 0, 1)                    # [B, T, P]
    return {"Projection": proj, "Cell": jnp.moveaxis(cs, 0, 1),
            "Hidden": proj,
            "BatchGate": None, "BatchCellPreAct": None, "BatchHidden": None}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("hinge_loss", diff_inputs=("Logits",))
def hinge_loss(ctx, op, ins):
    """operators/hinge_loss_op.cc: max(0, 1 - (2*label-1) * pred)."""
    pred = ins["Logits"][0]
    label = ins["Labels"][0].astype(pred.dtype)
    return {"Loss": jnp.maximum(0.0, 1.0 - (2.0 * label - 1.0) * pred)}


@register_op("bpr_loss", diff_inputs=("X",))
def bpr_loss(ctx, op, ins):
    """operators/bpr_loss_op.cc (session-based BPR): per row i with gold y,
    loss = -sum_{j != y} log(sigmoid(x_y - x_j)) / (D - 1)."""
    x = ins["X"][0]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    B, D = x.shape
    gold = jnp.take_along_axis(x, label[:, None], axis=1)    # [B, 1]
    diff = gold - x                                          # [B, D]
    ll = jnp.log1p(jnp.exp(-diff))  # -log(sigmoid(diff))
    mask = jnp.arange(D)[None, :] != label[:, None]
    loss = jnp.sum(jnp.where(mask, ll, 0.0), axis=1,
                   keepdims=True) / (D - 1)
    return {"Loss": loss.astype(x.dtype)}


@register_op("center_loss", diff_inputs=("X",))
def center_loss(ctx, op, ins):
    """operators/center_loss_op.h: loss = 0.5*||x - center_y||^2; centers
    move toward class means by CenterUpdateRate when need_update."""
    x = ins["X"][0]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    centers = ins["Centers"][0]
    rate = ins["CenterUpdateRate"][0].reshape(()) \
        if ins.get("CenterUpdateRate") else jnp.asarray(0.5, x.dtype)
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    out = {"Loss": loss.astype(x.dtype), "SampleCenterDiff": diff}
    if op.attr("need_update", True):
        counts = jnp.zeros((centers.shape[0],), x.dtype).at[label].add(1.0)
        delta = jnp.zeros_like(centers).at[label].add(diff)
        centers_new = centers + rate * delta / (counts[:, None] + 1.0)
        out["CentersOut"] = centers_new
    else:
        out["CentersOut"] = centers
    return out


@register_op("cross_entropy2", diff_inputs=("X",))
def cross_entropy2(ctx, op, ins):
    """operators/cross_entropy_op.cc (cross_entropy2): hard-label CE on
    probability input: -log(x[label]); emits MatchX for the grad kernel."""
    x = ins["X"][0]
    label = ins["Label"][0]
    ignore = int(op.attr("ignore_index", -100))
    lbl = label.reshape(label.shape[:x.ndim - 1])
    gather = jnp.take_along_axis(
        x, jnp.maximum(lbl, 0)[..., None].astype(jnp.int32), axis=-1)
    valid = (lbl != ignore)[..., None]
    match = jnp.where(valid, gather, 1.0)
    y = jnp.where(valid, -jnp.log(jnp.maximum(match, 1e-20)), 0.0)
    return {"Y": y.astype(x.dtype), "MatchX": match.astype(x.dtype),
            "XShape": None}


@register_op("teacher_student_sigmoid_loss", diff_inputs=("X",))
def teacher_student_sigmoid_loss(ctx, op, ins):
    """operators/teacher_student_sigmoid_loss_op.cc: CTR distillation loss —
    label<=0: log(1+exp(x)); else log(1+exp(x)) - x (hard part) plus the
    soft teacher term when 0<label<1."""
    x = ins["X"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1).astype(x.dtype)
    soft_max_up = float(op.attr("soft_max_up_bound", 15.0))
    soft_max_lo = float(op.attr("soft_max_lower_bound", -15.0))
    xs = jnp.clip(x, soft_max_lo, soft_max_up)
    log1pex = jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0.0)
    hard = jnp.where(label > 0.5, log1pex - x, log1pex)
    soft_label = (label > 0.0) & (label < 1.0)
    soft = jnp.where(soft_label,
                     jnp.log1p(jnp.exp(-jnp.abs(xs)))
                     + jnp.maximum(xs, 0.0) - label * xs, 0.0)
    out = jnp.where(soft_label, soft, hard)
    return {"Y": out.reshape(-1, 1).astype(x.dtype)}


# ---------------------------------------------------------------------------
# structure ops
# ---------------------------------------------------------------------------


@register_op("partial_concat", diff_inputs=("X",))
def partial_concat(ctx, op, ins):
    """operators/partial_concat_op.cc: concat X[i][:, start:start+length]."""
    start = int(op.attr("start_index", 0))
    length = int(op.attr("length", -1))
    outs = []
    for x in ins["X"]:
        end = x.shape[1] if length < 0 else start + length
        outs.append(x[:, start:end])
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op("partial_sum", diff_inputs=("X",))
def partial_sum(ctx, op, ins):
    """operators/partial_sum_op.cc: sum of X[i][:, start:start+length]."""
    start = int(op.attr("start_index", 0))
    length = int(op.attr("length", -1))
    acc = None
    for x in ins["X"]:
        end = x.shape[1] if length < 0 else start + length
        sl = x[:, start:end]
        acc = sl if acc is None else acc + sl
    return {"Out": acc}


@register_op("crop_tensor", diff_inputs=("X",))
def crop_tensor(ctx, op, ins):
    """operators/crop_tensor_op.cc: crop X to `shape` at `offsets`."""
    x = ins["X"][0]
    if ins.get("Shape"):
        shape = [int(v) for v in np.asarray(ins["Shape"][0])]
    else:
        shape = [int(v) for v in op.attr("shape")]
    if ins.get("Offsets"):
        offsets = [int(v) for v in np.asarray(ins["Offsets"][0])]
    else:
        offsets = [int(v) for v in op.attr("offsets", [0] * x.ndim)]
    shape = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    return {"Out": lax.slice(x, offsets,
                             [o + s for o, s in zip(offsets, shape)])}


@register_op("batch_fc", diff_inputs=("Input", "W", "Bias"))
def batch_fc(ctx, op, ins):
    """operators/batch_fc_op.cc: per-slot fc — Input [S, B, in],
    W [S, in, out], Bias [S, 1, out] (rank-attention serving stack)."""
    x, w = ins["Input"][0], ins["W"][0]
    out = jnp.einsum("sbi,sio->sbo", x, w)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": out}


@register_op("fsp", diff_inputs=("X", "Y"))
def fsp(ctx, op, ins):
    """operators/fsp_op.h: flow-of-solution-procedure matrix for
    distillation — (X_flat @ Y_flat^T) / (H*W); X [N,Cx,H,W], Y [N,Cy,H,W]
    -> [N, Cx, Cy]."""
    x, y = ins["X"][0], ins["Y"][0]
    N, cx, h, w = x.shape
    out = jnp.einsum("nxs,nys->nxy", x.reshape(N, cx, h * w),
                     y.reshape(N, y.shape[1], h * w)) / (h * w)
    return {"Out": out}


@register_op("row_conv", diff_inputs=("X", "Filter"))
def row_conv(ctx, op, ins):
    """operators/row_conv_op.cc: lookahead row convolution —
    out[t] = sum_w x[t+w] * filter[w] (elementwise over feature dim).
    Padded form: X [B, T, D], Filter [future_context, D]."""
    x = ins["X"][0]
    f = ins["Filter"][0]
    fc = f.shape[0]
    T = x.shape[1]
    pad = jnp.pad(x, ((0, 0), (0, fc - 1), (0, 0)))
    out = sum(pad[:, w:w + T, :] * f[w][None, None, :] for w in range(fc))
    return {"Out": out}


@register_op("conv_shift", diff_inputs=("X", "Y"))
def conv_shift(ctx, op, ins):
    """operators/conv_shift_op.cc: circular convolution —
    out[k,i] = sum_j x[k, (i+j-half) mod W] * y[k,j]."""
    x, y = ins["X"][0], ins["Y"][0]
    W = x.shape[1]
    yw = y.shape[1]
    half = (yw - 1) // 2
    idx = (jnp.arange(W)[:, None] + jnp.arange(yw)[None, :] - half) % W
    return {"Out": jnp.einsum("bij,bj->bi", x[:, idx], y)}


@register_op("spectral_norm", diff_inputs=("Weight",))
def spectral_norm(ctx, op, ins):
    """operators/spectral_norm_op.cc: W / sigma_max(W) via power iteration
    on the (U, V) buffers; iteration vectors are constants w.r.t. grad
    (stop_gradient), matching the reference kernel."""
    w = ins["Weight"][0]
    u = ins["U"][0].reshape(-1)
    v = ins["V"][0].reshape(-1)
    dim = int(op.attr("dim", 0))
    power_iters = int(op.attr("power_iters", 1))
    eps = float(op.attr("eps", 1e-12))
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)   # [h, w]

    def norm(a):
        return a / (jnp.linalg.norm(a) + eps)

    for _ in range(max(power_iters, 0)):
        v = norm(wm.T @ u)
        u = norm(wm @ v)
    u = lax.stop_gradient(u)
    v = lax.stop_gradient(v)
    sigma = u @ wm @ v
    return {"Out": w / sigma}


@register_op("cholesky", diff_inputs=("X",))
def cholesky(ctx, op, ins):
    """operators/cholesky_op.cc."""
    out = jnp.linalg.cholesky(ins["X"][0])
    if op.attr("upper", False):
        out = jnp.swapaxes(out, -1, -2)
    return {"Out": out}


@register_op("frobenius_norm", diff_inputs=("X",))
def frobenius_norm(ctx, op, ins):
    """operators/reduce_ops/frobenius_norm_op.cc."""
    x = ins["X"][0]
    dims = op.attr("dim", None)
    keep = bool(op.attr("keep_dim", False))
    if op.attr("reduce_all", False) or not dims:
        axes = None
    else:
        axes = tuple(d if d >= 0 else d + x.ndim for d in dims)
    return {"Out": jnp.sqrt(jnp.sum(jnp.square(x), axis=axes,
                                    keepdims=keep))}


@register_op("shard_index", grad=None)
def shard_index(ctx, op, ins):
    """operators/shard_index_op.cc: map global ids to shard-local ids."""
    x = ins["X"][0]
    index_num = int(op.attr("index_num"))
    nshards = int(op.attr("nshards"))
    shard_id = int(op.attr("shard_id"))
    ignore_value = int(op.attr("ignore_value", -1))
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return {"Out": jnp.where(in_shard, x % shard_size,
                             ignore_value).astype(x.dtype)}


@register_op("add_position_encoding", diff_inputs=("X",))
def add_position_encoding(ctx, op, ins):
    """operators/add_position_encoding_op.cc: sinusoidal PE —
    out = alpha*x + beta*PE, PE[pos, 2i] = sin(pos/10000^(2i/D)) with the
    reference's half-split layout (sin block then cos block)."""
    x = ins["X"][0]                                  # [B, T, D]
    alpha = float(op.attr("alpha", 1.0))
    beta = float(op.attr("beta", 1.0))
    B, T, D = x.shape
    half = D // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return {"Out": (alpha * x + beta * pe[None].astype(x.dtype))}


@register_op("space_to_depth", diff_inputs=("X",))
def space_to_depth(ctx, op, ins):
    """operators/space_to_depth_op.cc (blocksize rearrange, NCHW)."""
    x = ins["X"][0]
    bs = int(op.attr("blocksize"))
    N, C, H, W = x.shape
    x = x.reshape(N, C, H // bs, bs, W // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": x.reshape(N, C * bs * bs, H // bs, W // bs)}


@register_op("proximal_adagrad", grad=None, is_optimizer=True)
def proximal_adagrad(ctx, op, ins):
    """operators/optimizers/proximal_adagrad_op.cc."""
    p = ins["Param"][0]
    g = ins["Grad"][0]
    m = ins["Moment"][0]
    lr = ins["LearningRate"][0].reshape(())
    l1 = float(op.attr("l1", 0.0))
    l2 = float(op.attr("l2", 0.0))
    m_new = m + g * g
    lr_t = lr / jnp.sqrt(m_new)
    prox = p - lr_t * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(
            jnp.abs(prox) - lr_t * l1, 0.0)
    out = prox / (1.0 + lr_t * l2)
    return {"ParamOut": out, "MomentOut": m_new}
