"""Detection ops — parity with operators/detection/ (yolo_box, prior_box,
box_coder, roi_align as XLA lowerings; multiclass_nms as a HOST op — the
reference registers it CPU-only as well, multiclass_nms_op.cc, so variable-
size NMS output never touches the static-shape device graph).
"""
from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op


# ---------------------------------------------------------------------------
# yolo_box (detection/yolo_box_op.cc)
# ---------------------------------------------------------------------------

@register_op("yolo_box", grad=None)
def yolo_box(ctx, op, ins):
    x = ins["X"][0]                       # [N, an*(5+nc), H, W]
    img_size = ins["ImgSize"][0]          # [N, 2] (h, w)
    anchors = [int(a) for a in op.attr("anchors")]
    class_num = int(op.attr("class_num"))
    conf_thresh = float(op.attr("conf_thresh", 0.01))
    downsample = int(op.attr("downsample_ratio", 32))
    clip_bbox = bool(op.attr("clip_bbox", True))

    n, c, h, w = x.shape
    an = len(anchors) // 2
    x = x.reshape(n, an, 5 + class_num, h, w)
    x = jnp.transpose(x, (0, 1, 3, 4, 2))          # [N, an, H, W, 5+nc]

    grid_x = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]

    bx = (jax.nn.sigmoid(x[..., 0]) + grid_x) / w   # center, normalized
    by = (jax.nn.sigmoid(x[..., 1]) + grid_y) / h
    bw = jnp.exp(x[..., 2]) * aw / (downsample * w)
    bh = jnp.exp(x[..., 3]) * ah / (downsample * h)
    conf = jax.nn.sigmoid(x[..., 4])
    probs = jax.nn.sigmoid(x[..., 5:]) * conf[..., None]
    probs = jnp.where(conf[..., None] >= conf_thresh, probs, 0.0)

    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = probs.reshape(n, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


# ---------------------------------------------------------------------------
# prior_box (detection/prior_box_op.cc)
# ---------------------------------------------------------------------------

@register_op("prior_box", grad=None)
def prior_box(ctx, op, ins):
    feat = ins["Input"][0]                # [N, C, H, W]
    image = ins["Image"][0]               # [N, C, IH, IW]
    min_sizes = [float(s) for s in op.attr("min_sizes")]
    max_sizes = [float(s) for s in op.attr("max_sizes", [])]
    aspect_ratios = [float(a) for a in op.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    flip = bool(op.attr("flip", False))
    clip = bool(op.attr("clip", False))
    step_w = float(op.attr("step_w", 0.0))
    step_h = float(op.attr("step_h", 0.0))
    offset = float(op.attr("offset", 0.5))

    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h

    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - e) > 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    widths: List[float] = []
    heights: List[float] = []
    for k, ms in enumerate(min_sizes):
        # first: aspect ratio 1 with min size
        widths.append(ms); heights.append(ms)
        if max_sizes:
            prime = math.sqrt(ms * max_sizes[k])
            widths.append(prime); heights.append(prime)
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            widths.append(ms * math.sqrt(ar))
            heights.append(ms / math.sqrt(ar))
    num_priors = len(widths)

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sh
    cx = jnp.broadcast_to(cx[None, :, None], (h, w, num_priors))
    cy = jnp.broadcast_to(cy[:, None, None], (h, w, num_priors))
    bw = jnp.asarray(widths, jnp.float32)[None, None, :] / 2.0
    bh = jnp.asarray(heights, jnp.float32)[None, None, :] / 2.0
    boxes = jnp.stack([(cx - bw) / iw, (cy - bh) / ih,
                       (cx + bw) / iw, (cy + bh) / ih], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, num_priors, 4))
    return {"Boxes": boxes, "Variances": var}


# ---------------------------------------------------------------------------
# box_coder (detection/box_coder_op.cc)
# ---------------------------------------------------------------------------

@register_op("box_coder", grad=None)
def box_coder(ctx, op, ins):
    prior = ins["PriorBox"][0]            # [M, 4]
    pvar = ins.get("PriorBoxVar", [None])[0]
    target = ins["TargetBox"][0]
    code_type = op.attr("code_type", "encode_center_size")
    normalized = bool(op.attr("box_normalized", True))
    one = 0.0 if normalized else 1.0

    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        ox = (tcx - pcx) / pw / pvar[:, 0]
        oy = (tcy - pcy) / ph / pvar[:, 1]
        ow = jnp.log(tw / pw) / pvar[:, 2]
        oh = jnp.log(th / ph) / pvar[:, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    else:  # decode_center_size; target [M, 4] deltas
        dcx = target[..., 0] * pvar[:, 0] * pw + pcx
        dcy = target[..., 1] * pvar[:, 1] * ph + pcy
        dw = jnp.exp(target[..., 2] * pvar[:, 2]) * pw
        dh = jnp.exp(target[..., 3] * pvar[:, 3]) * ph
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - one, dcy + dh / 2 - one], axis=-1)
    return {"OutputBox": out}


# ---------------------------------------------------------------------------
# roi_align (detection/roi_align_op.cc)
# ---------------------------------------------------------------------------

@register_op("roi_align", diff_inputs=("X",))
def roi_align(ctx, op, ins):
    x = ins["X"][0]                        # [N, C, H, W]
    rois = ins["ROIs"][0]                  # [R, 4] (x1,y1,x2,y2)
    batch_ids = ins.get("RoisBatchId", [None])[0]
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    ratio = int(op.attr("sampling_ratio", -1))
    if ratio <= 0:
        ratio = 2
    if batch_ids is None:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
    n, c, h, w = x.shape

    def one_roi(roi, bid):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: [ph, ratio] x [pw, ratio]
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (jnp.arange(ratio)[None, :] + 0.5) * bin_h / ratio)
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (jnp.arange(ratio)[None, :] + 0.5) * bin_w / ratio)
        iy = iy.reshape(-1)                 # [ph*ratio]
        ix = ix.reshape(-1)                 # [pw*ratio]
        y0 = jnp.clip(jnp.floor(iy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(ix), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        ly = jnp.clip(iy - y0, 0.0, 1.0)
        lx = jnp.clip(ix - x0, 0.0, 1.0)
        img = x[bid]                        # [C, H, W]
        # bilinear: gather 4 corners on the outer product grid
        v00 = img[:, y0i[:, None], x0i[None, :]]
        v01 = img[:, y0i[:, None], x1i[None, :]]
        v10 = img[:, y1i[:, None], x0i[None, :]]
        v11 = img[:, y1i[:, None], x1i[None, :]]
        wy = ly[:, None]
        wx = lx[None, :]
        val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)   # [C, ph*r, pw*r]
        val = val.reshape(c, ph, ratio, pw, ratio).mean(axis=(2, 4))
        return val

    out = jax.vmap(one_roi)(rois, batch_ids)
    return {"Out": out}


# ---------------------------------------------------------------------------
# multiclass_nms — HOST op (CPU-only in the reference too)
# ---------------------------------------------------------------------------

def _nms_numpy(boxes, scores, iou_thresh, top_k):
    order = np.argsort(-scores)
    if top_k > 0:
        order = order[:top_k]
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[order[1:], 0])
        yy1 = np.maximum(boxes[i, 1], boxes[order[1:], 1])
        xx2 = np.minimum(boxes[i, 2], boxes[order[1:], 2])
        yy2 = np.minimum(boxes[i, 3], boxes[order[1:], 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        a = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        b = ((boxes[order[1:], 2] - boxes[order[1:], 0])
             * (boxes[order[1:], 3] - boxes[order[1:], 1]))
        iou = inter / np.maximum(a + b - inter, 1e-10)
        order = order[1:][iou <= iou_thresh]
    return keep


def _register_nms_host_op():
    from ..framework.executor import register_host_op

    @register_host_op("multiclass_nms")
    def multiclass_nms(scope, op, exe):
        import jax.numpy as jnp
        boxes = np.asarray(scope.find_var(op.input("BBoxes")[0]))   # [N,M,4]
        scores = np.asarray(scope.find_var(op.input("Scores")[0]))  # [N,C,M]
        score_thresh = float(op.attr("score_threshold", 0.0))
        nms_top_k = int(op.attr("nms_top_k", -1))
        keep_top_k = int(op.attr("keep_top_k", -1))
        iou = float(op.attr("nms_threshold", 0.3))
        background = int(op.attr("background_label", 0))
        outs = []
        for n in range(boxes.shape[0]):
            dets = []
            for cls in range(scores.shape[1]):
                if cls == background:
                    continue
                s = scores[n, cls]
                mask = s > score_thresh
                idx = np.nonzero(mask)[0]
                if idx.size == 0:
                    continue
                keep = _nms_numpy(boxes[n, idx], s[idx], iou, nms_top_k)
                for k in keep:
                    i = idx[k]
                    dets.append([float(cls), float(s[i]), *boxes[n, i]])
            dets.sort(key=lambda d: -d[1])
            if keep_top_k > 0:
                dets = dets[:keep_top_k]
            outs.extend(dets)
        out = (np.asarray(outs, np.float32) if outs
               else np.zeros((0, 6), np.float32))
        scope.set_var(op.output("Out")[0], jnp.asarray(out))


_register_nms_host_op()
