"""Detection ops — parity with operators/detection/ (yolo_box, prior_box,
box_coder, roi_align as XLA lowerings; multiclass_nms as a HOST op — the
reference registers it CPU-only as well, multiclass_nms_op.cc, so variable-
size NMS output never touches the static-shape device graph).
"""
from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import int_index_dtype
from ..framework.registry import register_op

_I64 = int_index_dtype  # call per use: jax_enable_x64 may toggle after import


# ---------------------------------------------------------------------------
# yolo_box (detection/yolo_box_op.cc)
# ---------------------------------------------------------------------------

@register_op("yolo_box", grad=None)
def yolo_box(ctx, op, ins):
    x = ins["X"][0]                       # [N, an*(5+nc), H, W]
    img_size = ins["ImgSize"][0]          # [N, 2] (h, w)
    anchors = [int(a) for a in op.attr("anchors")]
    class_num = int(op.attr("class_num"))
    conf_thresh = float(op.attr("conf_thresh", 0.01))
    downsample = int(op.attr("downsample_ratio", 32))
    clip_bbox = bool(op.attr("clip_bbox", True))

    n, c, h, w = x.shape
    an = len(anchors) // 2
    x = x.reshape(n, an, 5 + class_num, h, w)
    x = jnp.transpose(x, (0, 1, 3, 4, 2))          # [N, an, H, W, 5+nc]

    grid_x = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]

    bx = (jax.nn.sigmoid(x[..., 0]) + grid_x) / w   # center, normalized
    by = (jax.nn.sigmoid(x[..., 1]) + grid_y) / h
    bw = jnp.exp(x[..., 2]) * aw / (downsample * w)
    bh = jnp.exp(x[..., 3]) * ah / (downsample * h)
    conf = jax.nn.sigmoid(x[..., 4])
    probs = jax.nn.sigmoid(x[..., 5:]) * conf[..., None]
    probs = jnp.where(conf[..., None] >= conf_thresh, probs, 0.0)

    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = probs.reshape(n, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


# ---------------------------------------------------------------------------
# prior_box (detection/prior_box_op.cc)
# ---------------------------------------------------------------------------

@register_op("prior_box", grad=None)
def prior_box(ctx, op, ins):
    feat = ins["Input"][0]                # [N, C, H, W]
    image = ins["Image"][0]               # [N, C, IH, IW]
    min_sizes = [float(s) for s in op.attr("min_sizes")]
    max_sizes = [float(s) for s in op.attr("max_sizes", [])]
    aspect_ratios = [float(a) for a in op.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    flip = bool(op.attr("flip", False))
    clip = bool(op.attr("clip", False))
    step_w = float(op.attr("step_w", 0.0))
    step_h = float(op.attr("step_h", 0.0))
    offset = float(op.attr("offset", 0.5))

    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h

    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - e) > 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    widths: List[float] = []
    heights: List[float] = []
    for k, ms in enumerate(min_sizes):
        # first: aspect ratio 1 with min size
        widths.append(ms); heights.append(ms)
        if max_sizes:
            prime = math.sqrt(ms * max_sizes[k])
            widths.append(prime); heights.append(prime)
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            widths.append(ms * math.sqrt(ar))
            heights.append(ms / math.sqrt(ar))
    num_priors = len(widths)

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sh
    cx = jnp.broadcast_to(cx[None, :, None], (h, w, num_priors))
    cy = jnp.broadcast_to(cy[:, None, None], (h, w, num_priors))
    bw = jnp.asarray(widths, jnp.float32)[None, None, :] / 2.0
    bh = jnp.asarray(heights, jnp.float32)[None, None, :] / 2.0
    boxes = jnp.stack([(cx - bw) / iw, (cy - bh) / ih,
                       (cx + bw) / iw, (cy + bh) / ih], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, num_priors, 4))
    return {"Boxes": boxes, "Variances": var}


# ---------------------------------------------------------------------------
# box_coder (detection/box_coder_op.cc)
# ---------------------------------------------------------------------------

@register_op("box_coder", grad=None)
def box_coder(ctx, op, ins):
    prior = ins["PriorBox"][0]            # [M, 4]
    pvar = ins.get("PriorBoxVar", [None])[0]
    target = ins["TargetBox"][0]
    code_type = op.attr("code_type", "encode_center_size")
    normalized = bool(op.attr("box_normalized", True))
    one = 0.0 if normalized else 1.0

    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code_type.startswith("encode"):
        # ellipsis indexing: targets may be [M, 4] or batched [B, M, 4]
        # row-aligned against the [M, 4] priors (ssd_loss assigned targets)
        tw = target[..., 2] - target[..., 0] + one
        th = target[..., 3] - target[..., 1] + one
        tcx = target[..., 0] + tw / 2
        tcy = target[..., 1] + th / 2
        tw = jnp.maximum(tw, 1e-6)
        th = jnp.maximum(th, 1e-6)
        ox = (tcx - pcx) / pw / pvar[:, 0]
        oy = (tcy - pcy) / ph / pvar[:, 1]
        ow = jnp.log(tw / pw) / pvar[:, 2]
        oh = jnp.log(th / ph) / pvar[:, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    else:  # decode_center_size; target [M, 4] deltas
        dcx = target[..., 0] * pvar[:, 0] * pw + pcx
        dcy = target[..., 1] * pvar[:, 1] * ph + pcy
        dw = jnp.exp(target[..., 2] * pvar[:, 2]) * pw
        dh = jnp.exp(target[..., 3] * pvar[:, 3]) * ph
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - one, dcy + dh / 2 - one], axis=-1)
    return {"OutputBox": out}


# ---------------------------------------------------------------------------
# roi_align (detection/roi_align_op.cc)
# ---------------------------------------------------------------------------

@register_op("roi_align", diff_inputs=("X",))
def roi_align(ctx, op, ins):
    x = ins["X"][0]                        # [N, C, H, W]
    rois = ins["ROIs"][0]                  # [R, 4] (x1,y1,x2,y2)
    batch_ids = ins.get("RoisBatchId", [None])[0]
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    ratio = int(op.attr("sampling_ratio", -1))
    if ratio <= 0:
        from ..framework.core import get_flag

        if get_flag("FLAGS_roi_align_exact", False):
            return _roi_align_exact(x, rois, ins, op, ph, pw, scale)
        # The reference (detection/roi_align_op.cc) adaptively samples
        # ceil(roi_size/pooled_size) points per bin *per ROI* — a
        # data-dependent count XLA's static shapes cannot express. Use the
        # static upper bound of that formula (full-image ROI:
        # ceil(feature_size/pooled_size)), capped at 8 so fine feature
        # maps don't explode the sample grid: large ROIs are sampled at
        # (or beyond) reference density instead of the old fixed 2x2
        # under-sampling; outputs remain an average of the same bilinear
        # interpolant, just on a denser grid than the reference for small
        # ROIs.
        h_, w_ = int(x.shape[2]), int(x.shape[3])
        ratio = min(8, max(2, -(-h_ // ph), -(-w_ // pw)))
    if batch_ids is None:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
    n, c, h, w = x.shape

    def one_roi(roi, bid):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: [ph, ratio] x [pw, ratio]
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (jnp.arange(ratio)[None, :] + 0.5) * bin_h / ratio)
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (jnp.arange(ratio)[None, :] + 0.5) * bin_w / ratio)
        iy = iy.reshape(-1)                 # [ph*ratio]
        ix = ix.reshape(-1)                 # [pw*ratio]
        y0 = jnp.clip(jnp.floor(iy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(ix), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        ly = jnp.clip(iy - y0, 0.0, 1.0)
        lx = jnp.clip(ix - x0, 0.0, 1.0)
        img = x[bid]                        # [C, H, W]
        # bilinear: gather 4 corners on the outer product grid
        v00 = img[:, y0i[:, None], x0i[None, :]]
        v01 = img[:, y0i[:, None], x1i[None, :]]
        v10 = img[:, y1i[:, None], x0i[None, :]]
        v11 = img[:, y1i[:, None], x1i[None, :]]
        wy = ly[:, None]
        wx = lx[None, :]
        val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)   # [C, ph*r, pw*r]
        val = val.reshape(c, ph, ratio, pw, ratio).mean(axis=(2, 4))
        return val

    out = jax.vmap(one_roi)(rois, batch_ids)
    return {"Out": out}


def _roi_align_exact(x, rois, ins, op, ph, pw, scale):
    """Exact reference adaptive sampling (roi_align_op.cu ceil(roi/pooled)
    per ROI) under static shapes: sample a [ph, K] x [pw, K] super-grid
    where K is the static worst case, with per-ROI dynamic positions
    (j+0.5)*bin/k and weights (j<k)/k — slots past this ROI's own k carry
    zero weight, so the weighted sum equals the reference's k-point
    average exactly. FLAGS_roi_align_exact opts in (K^2 denser gather
    than the bounded default)."""
    batch_ids = ins.get("RoisBatchId", [None])[0]
    if batch_ids is None:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
    n, c, h, w = x.shape
    # static worst-case grid: ROIs are normally clipped to the image, so
    # ceil(feature/pooled) covers them; unclipped over-image ROIs would
    # need a larger bound — raise FLAGS_roi_align_exact_scale (x the
    # image-derived bound) for those, at proportionally higher gather cost
    from ..framework.core import get_flag

    over = max(1, int(get_flag("FLAGS_roi_align_exact_scale", 1) or 1))
    Ky = max(1, -(-h // ph)) * over
    Kx = max(1, -(-w // pw)) * over

    def one_roi(roi, bid):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        ky = jnp.clip(jnp.ceil(bin_h), 1, Ky)            # samples per bin
        kx = jnp.clip(jnp.ceil(bin_w), 1, Kx)
        jy = jnp.arange(Ky, dtype=x.dtype)
        jx = jnp.arange(Kx, dtype=x.dtype)
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (jy[None, :] + 0.5) * bin_h / ky)        # [ph, Ky]
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (jx[None, :] + 0.5) * bin_w / kx)        # [pw, Kx]
        wy = jnp.where(jy < ky, 1.0 / ky, 0.0)           # [Ky]
        wx = jnp.where(jx < kx, 1.0 / kx, 0.0)           # [Kx]
        iy = iy.reshape(-1)
        ix = ix.reshape(-1)
        y0 = jnp.clip(jnp.floor(iy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(ix), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        ly = jnp.clip(iy - y0, 0.0, 1.0)
        lx = jnp.clip(ix - x0, 0.0, 1.0)
        img = x[bid]
        v00 = img[:, y0i[:, None], x0i[None, :]]
        v01 = img[:, y0i[:, None], x1i[None, :]]
        v10 = img[:, y1i[:, None], x0i[None, :]]
        v11 = img[:, y1i[:, None], x1i[None, :]]
        gy = ly[:, None]
        gx = lx[None, :]
        val = (v00 * (1 - gy) * (1 - gx) + v01 * (1 - gy) * gx
               + v10 * gy * (1 - gx) + v11 * gy * gx)
        val = val.reshape(c, ph, Ky, pw, Kx)
        return jnp.einsum("cpyqx,y,x->cpq", val, wy, wx)

    out = jax.vmap(one_roi)(rois, batch_ids)
    return {"Out": out}


# ---------------------------------------------------------------------------
# multiclass_nms — HOST op (CPU-only in the reference too)
# ---------------------------------------------------------------------------

def _nms_numpy(boxes, scores, iou_thresh, top_k):
    order = np.argsort(-scores)
    if top_k > 0:
        order = order[:top_k]
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[order[1:], 0])
        yy1 = np.maximum(boxes[i, 1], boxes[order[1:], 1])
        xx2 = np.minimum(boxes[i, 2], boxes[order[1:], 2])
        yy2 = np.minimum(boxes[i, 3], boxes[order[1:], 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        a = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        b = ((boxes[order[1:], 2] - boxes[order[1:], 0])
             * (boxes[order[1:], 3] - boxes[order[1:], 1]))
        iou = inter / np.maximum(a + b - inter, 1e-10)
        order = order[1:][iou <= iou_thresh]
    return keep


def _register_nms_host_op():
    from ..framework.executor import register_host_op

    @register_host_op("multiclass_nms")
    def multiclass_nms(scope, op, exe):
        import jax.numpy as jnp
        boxes = np.asarray(scope.find_var(op.input("BBoxes")[0]))   # [N,M,4]
        scores = np.asarray(scope.find_var(op.input("Scores")[0]))  # [N,C,M]
        score_thresh = float(op.attr("score_threshold", 0.0))
        nms_top_k = int(op.attr("nms_top_k", -1))
        keep_top_k = int(op.attr("keep_top_k", -1))
        iou = float(op.attr("nms_threshold", 0.3))
        background = int(op.attr("background_label", 0))
        outs = []
        for n in range(boxes.shape[0]):
            dets = []
            for cls in range(scores.shape[1]):
                if cls == background:
                    continue
                s = scores[n, cls]
                mask = s > score_thresh
                idx = np.nonzero(mask)[0]
                if idx.size == 0:
                    continue
                keep = _nms_numpy(boxes[n, idx], s[idx], iou, nms_top_k)
                for k in keep:
                    i = idx[k]
                    dets.append([float(cls), float(s[i]), *boxes[n, i]])
            dets.sort(key=lambda d: -d[1])
            if keep_top_k > 0:
                dets = dets[:keep_top_k]
            outs.extend(dets)
        out = (np.asarray(outs, np.float32) if outs
               else np.zeros((0, 6), np.float32))
        scope.set_var(op.output("Out")[0], jnp.asarray(out))


_register_nms_host_op()


# ---------------------------------------------------------------------------
# anchor_generator (detection/anchor_generator_op.{cc,h})
# ---------------------------------------------------------------------------

@register_op("anchor_generator", grad=None)
def anchor_generator(ctx, op, ins):
    """Anchors [H,W,A,4] in (x1,y1,x2,y2); loop order ratios-outer,
    sizes-inner per anchor_generator_op.h:62-84."""
    x = ins["Input"][0]                    # [N, C, H, W]
    sizes = [float(v) for v in op.attr("anchor_sizes")]
    ratios = [float(v) for v in op.attr("aspect_ratios")]
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    stride = [float(v) for v in op.attr("stride")]
    offset = float(op.attr("offset", 0.5))
    H, W = int(x.shape[2]), int(x.shape[3])
    sw, sh = stride[0], stride[1]

    wh = []
    for ar in ratios:
        area = sw * sh
        base_w = jnp.round(jnp.sqrt(area / ar))
        base_h = jnp.round(base_w * ar)
        for size in sizes:
            wh.append((size / sw * base_w, size / sh * base_h))
    aw = jnp.stack([p[0] for p in wh]).astype(jnp.float32)   # [A]
    ah = jnp.stack([p[1] for p in wh]).astype(jnp.float32)
    xc = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)  # [W]
    yc = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)  # [H]
    xg = xc[None, :, None]
    yg = yc[:, None, None]
    coords = jnp.broadcast_arrays(
        xg - 0.5 * (aw - 1), yg - 0.5 * (ah - 1),
        xg + 0.5 * (aw - 1), yg + 0.5 * (ah - 1))
    anchors = jnp.broadcast_to(jnp.stack(coords, axis=-1),
                               (H, W, len(wh), 4))
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, len(wh), 4))
    return {"Anchors": anchors, "Variances": var}


# ---------------------------------------------------------------------------
# density_prior_box (detection/density_prior_box_op.h)
# ---------------------------------------------------------------------------

@register_op("density_prior_box", grad=None)
def density_prior_box(ctx, op, ins):
    x = ins["Input"][0]                    # [N, C, H, W]
    img = ins["Image"][0]                  # [N, C, Him, Wim]
    fixed_sizes = [float(v) for v in op.attr("fixed_sizes", [])]
    fixed_ratios = [float(v) for v in op.attr("fixed_ratios", [])]
    densities = [int(v) for v in op.attr("densities", [])]
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = bool(op.attr("clip", True))
    offset = float(op.attr("offset", 0.5))
    H, W = int(x.shape[2]), int(x.shape[3])
    img_h, img_w = float(img.shape[2]), float(img.shape[3])
    step_w = float(op.attr("step_w", 0.0)) or img_w / W
    step_h = float(op.attr("step_h", 0.0)) or img_h / H
    step_average = int((step_w + step_h) * 0.5)

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w   # [W]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h   # [H]
    boxes_per_cell = []
    for size, density in zip(fixed_sizes, densities):
        shift = step_average // density
        for ratio in fixed_ratios:
            bw = size * float(np.sqrt(ratio))
            bh = size / float(np.sqrt(ratio))
            d0x = -step_average / 2.0 + shift / 2.0
            d0y = -step_average / 2.0 + shift / 2.0
            for di in range(density):
                for dj in range(density):
                    boxes_per_cell.append((d0x + dj * shift, d0y + di * shift,
                                           bw, bh))
    A = len(boxes_per_cell)
    dx = jnp.asarray([b[0] for b in boxes_per_cell], jnp.float32)
    dy = jnp.asarray([b[1] for b in boxes_per_cell], jnp.float32)
    bw = jnp.asarray([b[2] for b in boxes_per_cell], jnp.float32)
    bh = jnp.asarray([b[3] for b in boxes_per_cell], jnp.float32)
    cxg = cx[None, :, None] + dx                     # [1,W,A]
    cyg = cy[:, None, None] + dy                     # [H,1,A]
    x1 = (cxg - bw / 2.0) / img_w
    y1 = (cyg - bh / 2.0) / img_h
    x2 = (cxg + bw / 2.0) / img_w
    y2 = (cyg + bh / 2.0) / img_h
    x1, x2 = jnp.maximum(x1, 0.0), jnp.minimum(x2, 1.0)
    y1, y2 = jnp.maximum(y1, 0.0), jnp.minimum(y2, 1.0)
    boxes = jnp.broadcast_to(
        jnp.stack(jnp.broadcast_arrays(x1, y1, x2, y2), axis=-1),
        (H, W, A, 4))
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), (H, W, A, 4))
    if bool(op.attr("flatten_to_2d", False)):
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return {"Boxes": boxes, "Variances": var}


# ---------------------------------------------------------------------------
# roi_pool (roi_pool_op.h) — static-shape max pool per ROI bin
# ---------------------------------------------------------------------------

@register_op("roi_pool", diff_inputs=("X",))
def roi_pool(ctx, op, ins):
    """Per-bin max via a mask over the full (static) H x W grid — the
    TPU-native shape for the reference's dynamic-extent bin loops."""
    x = ins["X"][0]                        # [N, C, H, W]
    rois = ins["ROIs"][0]                  # [R, 4]
    batch_ids = ins.get("RoisBatchId", [None])[0]
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    if batch_ids is None:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
    n, c, h, w = x.shape
    hs = jnp.arange(h)
    ws = jnp.arange(w)

    def one_roi(roi, bid):
        rx1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        ry1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        rx2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        ry2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(ry2 - ry1 + 1, 1)
        rw = jnp.maximum(rx2 - rx1 + 1, 1)
        bin_h = rh.astype(jnp.float32) / ph
        bin_w = rw.astype(jnp.float32) / pw
        pidx = jnp.arange(ph, dtype=jnp.float32)
        qidx = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(pidx * bin_h).astype(jnp.int32) + ry1,
                          0, h)                        # [ph]
        hend = jnp.clip(jnp.ceil((pidx + 1) * bin_h).astype(jnp.int32) + ry1,
                        0, h)
        wstart = jnp.clip(jnp.floor(qidx * bin_w).astype(jnp.int32) + rx1,
                          0, w)                        # [pw]
        wend = jnp.clip(jnp.ceil((qidx + 1) * bin_w).astype(jnp.int32) + rx1,
                        0, w)
        hmask = (hs[None, :] >= hstart[:, None]) & (hs[None, :] < hend[:, None])
        wmask = (ws[None, :] >= wstart[:, None]) & (ws[None, :] < wend[:, None])
        img = x[bid]                                   # [C, H, W]
        # bins are rectangles, so the max is separable: reduce rows first
        # ([C,ph,W]) then columns ([C,ph,pw]) — a ph*pw-fold smaller
        # intermediate than masking the full [ph,pw,H,W] grid at once
        rowm = jnp.where(hmask[None, :, :, None], img[:, None],
                         -jnp.inf)                     # [C,ph,H,W]
        rowmax = rowm.max(axis=2)                      # [C,ph,W]
        rowarg = rowm.argmax(axis=2)                   # [C,ph,W] -> h index
        colm = jnp.where(wmask[None, None], rowmax[:, :, None, :],
                         -jnp.inf)                     # [C,ph,pw,W]
        val = colm.max(axis=-1)                        # [C,ph,pw]
        warg = colm.argmax(axis=-1)                    # [C,ph,pw] -> w index
        harg = jnp.take_along_axis(rowarg, warg, axis=-1)  # [C,ph,pw]
        arg = (harg * w + warg).astype(_I64())
        empty = ~(hmask.any(-1)[:, None] & wmask.any(-1)[None, :])  # [ph,pw]
        val = jnp.where(empty[None], 0.0, val)
        arg = jnp.where(empty[None], -1, arg)
        return val, arg

    out, argmax = jax.vmap(one_roi)(rois, batch_ids)
    return {"Out": out, "Argmax": argmax}


# ---------------------------------------------------------------------------
# iou_similarity / box_clip / sigmoid_focal_loss
# ---------------------------------------------------------------------------

@register_op("iou_similarity", grad=None)
def iou_similarity(ctx, op, ins):
    """detection/iou_similarity_op.h: pairwise IoU [N, M]."""
    a = ins["X"][0]                        # [N,4] or [B, N, 4]
    b = ins["Y"][0]                        # [M,4]
    norm = bool(op.attr("box_normalized", True))
    off = 0.0 if norm else 1.0
    ax1, ay1, ax2, ay2 = [a[..., i][..., :, None] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1, bx1)
    iy1 = jnp.maximum(ay1, by1)
    ix2 = jnp.minimum(ax2, bx2)
    iy2 = jnp.minimum(ay2, by2)
    inter = (jnp.maximum(ix2 - ix1 + off, 0.0)
             * jnp.maximum(iy2 - iy1 + off, 0.0))
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    return {"Out": inter / jnp.maximum(area_a + area_b - inter, 1e-10)}


@register_op("box_clip", grad=None)
def box_clip(ctx, op, ins):
    """detection/box_clip_op.h: clip boxes to image (im_h-1, im_w-1).

    Batched boxes [N, M, 4] clip each image against its own im_info row;
    flat boxes [M, 4] use im_info[0] (single-image case).
    """
    boxes = ins["Input"][0]                # [M, 4] or [N, M, 4]
    im_info = ins["ImInfo"][0]             # [N, 3] (h, w, scale)
    # boxes live in the ORIGINAL image frame: divide the (resized) im_info
    # dims by the scale factor first (bbox_util.h:137 ClipTiledBoxes)
    imh = jnp.round(im_info[:, 0] / im_info[:, 2])
    imw = jnp.round(im_info[:, 1] / im_info[:, 2])
    if boxes.ndim == 3:
        h = (imh - 1.0)[:, None]           # [N,1]
        w = (imw - 1.0)[:, None]
    else:
        h = imh[0] - 1.0
        w = imw[0] - 1.0
    x1 = jnp.clip(boxes[..., 0], 0.0, w)
    y1 = jnp.clip(boxes[..., 1], 0.0, h)
    x2 = jnp.clip(boxes[..., 2], 0.0, w)
    y2 = jnp.clip(boxes[..., 3], 0.0, h)
    return {"Output": jnp.stack([x1, y1, x2, y2], axis=-1)}


@register_op("sigmoid_focal_loss", diff_inputs=("X",))
def sigmoid_focal_loss(ctx, op, ins):
    """detection/sigmoid_focal_loss_op.cu math on dense labels.

    X [N, C] logits; Label [N, 1] int (0 = background, c>=1 -> class c-1,
    -1 = ignore — contributes no loss, sigmoid_focal_loss_op.cu:53-54);
    FgNum [1] normalizer.
    """
    x = ins["X"][0]
    label = ins["Label"][0].reshape(-1)
    fg = jnp.maximum(ins["FgNum"][0].astype(jnp.float32).reshape(()), 1.0)
    gamma = float(op.attr("gamma", 2.0))
    alpha = float(op.attr("alpha", 0.25))
    n, c = x.shape
    pos = jax.nn.one_hot(label - 1, c, dtype=x.dtype)   # label<=0 -> all zero
    neg = jnp.where((label != -1)[:, None], 1.0 - pos, 0.0)
    p = jax.nn.sigmoid(x)
    # stable log-sigmoid forms (clip(p) would flatline the gradient for
    # confident negatives, |x| > ~17 in float32)
    ce_pos = jax.nn.softplus(-x)           # -log(sigmoid(x))
    ce_neg = jax.nn.softplus(x)            # -log(1 - sigmoid(x))
    loss = (pos * alpha * (1 - p) ** gamma * ce_pos
            + neg * (1 - alpha) * p ** gamma * ce_neg)
    return {"Out": loss / fg}
