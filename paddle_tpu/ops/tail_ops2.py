"""Op tail, batch 2 — inference-graph and slim-int8 kernels closing the
REGISTER_OPERATOR name diff: fc, fused_batch_norm_act,
fused_fc_elementwise_layernorm, fusion_transpose_flatten_concat,
fusion_seqpool_cvm_concat, dequantize_abs_max, dequantize_log,
lookup_table_dequant, fill_zeros_like2, fake_init, seed; host ops
delete_var, get_places, locality_aware_nms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.core import dtype_to_jax
from ..framework.executor import register_host_op
from ..framework.registry import register_op, get_op_spec


@register_op("fc", diff_inputs=("Input", "W", "Bias"))
def fc(ctx, op, ins):
    """operators/fc_op.cc — the fused inference-graph fc (the training
    graph uses mul+elementwise_add; fuse passes rewrite to this)."""
    x, w = ins["Input"][0], ins["W"][0]
    ncol = int(op.attr("in_num_col_dims", 1))
    act = str(op.attr("activation_type", "") or "")
    lead = x.shape[:ncol]
    x2 = x.reshape(int(np.prod(lead)), -1)
    out = x2 @ w
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(1, -1)
    if act == "relu":
        out = jax.nn.relu(out)
    elif act:
        raise NotImplementedError(f"fc activation {act!r}")
    return {"Out": out.reshape(tuple(lead) + (w.shape[1],))}


@register_op("fused_batch_norm_act", diff_inputs=("X", "Scale", "Bias"))
def fused_batch_norm_act(ctx, op, ins):
    """operators/fused/fused_bn_activation_op.cc — batch_norm + activation
    in one op (cuDNN-fused in the reference; XLA fuses the composition)."""
    outs = get_op_spec("batch_norm").lower(ctx, op, ins)
    act = str(op.attr("act_type", "relu"))
    y = outs.get("Y")
    if act == "relu":
        y = jax.nn.relu(y)
    elif act in ("sigmoid", "tanh"):
        y = jax.nn.sigmoid(y) if act == "sigmoid" else jnp.tanh(y)
    outs["Y"] = y
    return outs


@register_op("fused_fc_elementwise_layernorm",
             diff_inputs=("X", "W", "Bias0", "Y", "Scale", "Bias1"))
def fused_fc_elementwise_layernorm(ctx, op, ins):
    """operators/fused/fused_fc_elementwise_layernorm_op.cc —
    layer_norm(fc(X, W, Bias0) + Y) with affine Scale/Bias1."""
    x, w = ins["X"][0], ins["W"][0]
    ncol = int(op.attr("x_num_col_dims", 1))
    eps = float(op.attr("epsilon", 1e-5))
    begin = int(op.attr("begin_norm_axis", 1))
    lead = x.shape[:ncol]
    out = x.reshape(int(np.prod(lead)), -1) @ w
    if ins.get("Bias0"):
        out = out + ins["Bias0"][0].reshape(1, -1)
    act = str(op.attr("activation_type", "") or "")
    if act == "relu":
        out = jax.nn.relu(out)
    out = out.reshape(tuple(lead) + (w.shape[1],))
    y = ins["Y"][0]
    z = out + y
    shape = z.shape
    z2 = z.reshape(int(np.prod(shape[:begin])), -1)
    mean = jnp.mean(z2, axis=1, keepdims=True)
    var = jnp.var(z2, axis=1, keepdims=True)
    norm = (z2 - mean) * lax.rsqrt(var + eps)
    if ins.get("Scale"):
        norm = norm * ins["Scale"][0].reshape(1, -1)
    if ins.get("Bias1"):
        norm = norm + ins["Bias1"][0].reshape(1, -1)
    return {"Out": norm.reshape(shape), "Mean": mean.reshape(-1),
            "Variance": var.reshape(-1)}


@register_op("fusion_transpose_flatten_concat", diff_inputs=("X",))
def fusion_transpose_flatten_concat(ctx, op, ins):
    """operators/fused/fusion_transpose_flatten_concat_op.cc — per input:
    transpose(trans_axis) -> flatten2(flatten_axis), then concat."""
    trans = [int(a) for a in op.attr("trans_axis", [])]
    flat_axis = int(op.attr("flatten_axis", 1))
    concat_axis = int(op.attr("concat_axis", 1))
    pieces = []
    for x in ins["X"]:
        t = jnp.transpose(x, trans) if trans else x
        lead = int(np.prod(t.shape[:flat_axis]))
        pieces.append(t.reshape(lead, -1))
    return {"Out": jnp.concatenate(pieces, axis=concat_axis)}


@register_op("fusion_seqpool_cvm_concat", diff_inputs=("X",))
def fusion_seqpool_cvm_concat(ctx, op, ins):
    """operators/fused/fusion_seqpool_cvm_concat_op.cc — per input sequence
    sum-pool, CVM transform, concat (CTR serving path). Padded [B,T,D]
    inputs; CVM keeps width (use_cvm=True layout: cols 0,1 are show/click
    -> log transforms, ops/ctr.py cvm)."""
    use_cvm = bool(op.attr("use_cvm", True))
    cvm_spec = get_op_spec("cvm")
    pool_spec = get_op_spec("sequence_pool")
    # Padded convention: optional Lengths (one (B,) tensor per X, or a single
    # shared one) carries each sequence's true length — the reference divides
    # AVERAGE by the LoD length, not the padded extent.  The masked-length
    # pooling itself is sequence_pool's job (same pooltype attr contract).
    lengths = ins.get("Lengths") or ins.get("Length") or []
    if lengths and len(lengths) not in (1, len(ins["X"])):
        raise ValueError(
            f"fusion_seqpool_cvm_concat: got {len(lengths)} Lengths for "
            f"{len(ins['X'])} X inputs (want 1 shared or one per input)")
    pieces = []
    for i, x in enumerate(ins["X"]):
        if x.ndim == 3:
            pool_ins = {"X": [x]}
            if lengths:
                pool_ins["Length"] = [
                    lengths[i] if len(lengths) > 1 else lengths[0]]
            p = pool_spec.lower(ctx, op, pool_ins)["Out"]
        else:
            p = x
        if use_cvm:
            p = cvm_spec.lower(ctx, op, {"X": [p], "CVM": ins.get("CVM")}
                               )["Y"]
        pieces.append(p)
    return {"Out": jnp.concatenate(pieces, axis=1)}


# ---------------------------------------------------------------------------
# slim int8 persistence kernels
# ---------------------------------------------------------------------------

@register_op("dequantize_abs_max", grad=None)
def dequantize_abs_max(ctx, op, ins):
    """operators/dequantize_abs_max_op.cc — int8 codes * scale/max_range."""
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = float(op.attr("max_range", 127.0))
    return {"Out": x.astype(jnp.float32) * scale / max_range}


@register_op("dequantize_log", grad=None)
def dequantize_log(ctx, op, ins):
    """operators/dequantize_log_op.cc:84 — signed log-table lookup:
    out = x < 0 ? -dict[x+128] : dict[x]."""
    x = ins["X"][0].astype(jnp.int32)
    table = ins["Dict"][0].reshape(-1)
    return {"Out": jnp.where(x < 0, -table[x + 128], table[x])}


@register_op("lookup_table_dequant", grad=None)
def lookup_table_dequant(ctx, op, ins):
    """operators/lookup_table_dequant_op.h:40 — embedding rows stored as
    [min, max, uint8x4 codes...] float32; dequant x = (max-min)/256*code
    + min. bitcast float32->uint8x4 replaces the reference's pointer
    reinterpret."""
    ids = ins["Ids"][0]
    w = ins["W"][0]
    padding_idx = int(op.attr("padding_idx", -1))
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    idx = ids.astype(jnp.int32)
    rows = w[jnp.clip(idx, 0, w.shape[0] - 1)]          # [..., Q]
    mn = rows[..., 0:1]
    mx = rows[..., 1:2]
    codes = lax.bitcast_convert_type(rows[..., 2:], jnp.uint8)
    codes = codes.reshape(codes.shape[:-2] + (-1,)).astype(jnp.float32)
    out = (mx - mn) / 256.0 * codes + mn
    if padding_idx >= 0:
        out = jnp.where((idx == padding_idx)[..., None], 0.0, out)
    return {"Out": out}


# ---------------------------------------------------------------------------
# trivial program-parity kernels
# ---------------------------------------------------------------------------

@register_op("fill_zeros_like2", grad=None)
def fill_zeros_like2(ctx, op, ins):
    """operators/fill_zeros_like_op.cc (variant with dtype attr)."""
    dt = dtype_to_jax(op.attr("dtype", 5))
    return {"Out": jnp.zeros(ins["X"][0].shape, dt)}


@register_op("fake_init", grad=None)
def fake_init(ctx, op, ins):
    """operators/fill_constant_op.cc sibling fake_init_op.cc — placeholder
    init on PS trainers (the server owns the real values)."""
    shape = [int(s) for s in op.attr("shape", [1])]
    dt = dtype_to_jax(op.attr("dtype", 5))
    return {"Out": jnp.zeros(shape, dt)}


@register_op("seed", grad=None, needs_rng=True)
def seed_op(ctx, op, ins):
    """operators/seed_op.cc — emit an int32 seed (attr if nonzero, else a
    fresh draw from the program rng stream)."""
    s = int(op.attr("seed", 0))
    if s != 0:
        return {"Out": jnp.asarray([s], jnp.int32)}
    key = ctx.rng_for(op)
    return {"Out": jax.random.randint(key, (1,), 1, 2 ** 31 - 1,
                                      dtype=jnp.int32)}


# ---------------------------------------------------------------------------
# host ops
# ---------------------------------------------------------------------------

@register_host_op("delete_var")
def delete_var(scope, op, exe):
    """controlflow/op_variant.h delete_var_op — drop vars from the scope."""
    for name in op.input("X"):
        if hasattr(scope, "erase_var"):
            scope.erase_var(name)
        else:
            v = scope.find_var(name)
            if v is not None:
                scope.set_var(name, None)


@register_host_op("get_places")
def get_places(scope, op, exe):
    """operators/get_places_op.cc — device-count introspection (ParallelDo
    era); emits the visible device count."""
    import jax

    n = int(op.attr("device_count", 0)) or len(jax.devices())
    scope.set_var(op.output("Out")[0], np.asarray([n], np.int64))


@register_host_op("locality_aware_nms")
def locality_aware_nms(scope, op, exe):
    """detection/locality_aware_nms_op.cc — multiclass NMS that first
    fuses same-class overlapping detections (score-weighted box average),
    as used by EAST-style text detection."""
    boxes = np.asarray(scope.find_var(op.input("BBoxes")[0]))    # [N,M,4]
    scores = np.asarray(scope.find_var(op.input("Scores")[0]))   # [N,C,M]
    score_thresh = float(op.attr("score_threshold", 0.0))
    nms_top_k = int(op.attr("nms_top_k", -1))
    keep_top_k = int(op.attr("keep_top_k", -1))
    iou_thr = float(op.attr("nms_threshold", 0.3))
    background = int(op.attr("background_label", -1))

    def iou(a, b):
        x1, y1 = max(a[0], b[0]), max(a[1], b[1])
        x2, y2 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    outs = []
    for n in range(boxes.shape[0]):
        dets = []
        for cls in range(scores.shape[1]):
            if cls == background:
                continue
            s = scores[n, cls]
            idx = np.nonzero(s > score_thresh)[0]
            if idx.size == 0:
                continue
            order = idx[np.argsort(-s[idx])]
            if nms_top_k > 0:
                order = order[:nms_top_k]
            cand = [[s[i], boxes[n, i].astype(np.float64).copy()]
                    for i in order]
            # locality-aware merge: weighted-average consecutive overlaps
            merged = []
            for sc, box in cand:
                if merged and iou(merged[-1][1], box) > iou_thr:
                    psc, pbox = merged[-1]
                    tot = psc + sc
                    merged[-1] = [tot, (pbox * psc + box * sc) / tot] \
                        if tot > 0 else [tot, pbox]
                else:
                    merged.append([sc, box])
            merged.sort(key=lambda d: -d[0])
            keep = []
            for sc, box in merged:
                if all(iou(box, kb) <= iou_thr for _, kb in keep):
                    keep.append((sc, box))
            for sc, box in keep:
                dets.append([float(cls), float(sc), *box.tolist()])
        dets.sort(key=lambda d: -d[1])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        outs.extend(dets)
    import jax.numpy as jnp_

    out = (np.asarray(outs, np.float32) if outs
           else np.zeros((0, 6), np.float32))
    scope.set_var(op.output("Out")[0], jnp_.asarray(out))


# hierarchical_sigmoid_op.cc registers this full name; the layer-emitted
# short form "hsigmoid" shares the lowering
from .control_flow import _alias_op  # noqa: E402

_alias_op("hierarchical_sigmoid", "hsigmoid",
          diff_inputs=("X", "W", "Bias"))


@register_op("conv2d_fusion", diff_inputs=("Input", "Filter", "Bias"))
def conv2d_fusion(ctx, op, ins):
    """fused/conv2d_fusion_op.cc (cuDNN fused conv+bias+act+residual in
    the reference's inference graphs) — conv2d lowering + epilogue; XLA
    re-fuses the epilogue into the conv."""
    outs = get_op_spec("conv2d").lower(ctx, op, ins)
    out = outs["Output"]
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(1, -1, 1, 1)
    if ins.get("ResidualData"):
        out = out + ins["ResidualData"][0]
    act = str(op.attr("activation", "relu") or "identity")
    if act == "relu":
        out = jax.nn.relu(out)
    elif act not in ("identity", ""):
        raise NotImplementedError(f"conv2d_fusion activation {act!r}")
    return {"Output": out}


@register_host_op("feed")
def feed_op(scope, op, exe):
    """operators/feed_op.cc — move feed-holder column `col` into the out
    var. The executor's feed dict usually binds out vars directly; this
    shim makes persisted programs with explicit feed ops runnable."""
    out = op.output("Out")[0]
    if scope.find_var(out) is not None:
        return                              # already fed by name
    holder = scope.find_var(op.input("X")[0])
    if holder is None:
        raise RuntimeError(f"feed op: neither {out!r} nor the feed holder "
                           "is present in scope")
    col = int(op.attr("col", 0))
    scope.set_var(out, holder[col])


@register_host_op("fetch")
def fetch_op(scope, op, exe):
    """operators/fetch_op.cc — copy the in var into the fetch holder."""
    x = scope.find_var(op.input("X")[0])
    holder_name = op.output("Out")[0]
    holder = scope.find_var(holder_name)
    col = int(op.attr("col", 0))
    lst = list(holder) if isinstance(holder, (list, tuple)) else []
    while len(lst) <= col:
        lst.append(None)
    lst[col] = x
    scope.set_var(holder_name, lst)
