"""Long-tail op batch 3: full-sequence lstm/gru (reference top-level op
names), deformable convolution v1/v2, position-sensitive / precise RoI
pooling, inplace ABN.

Same design rules as nn_extra.py: padded [B, T, ...] sequences, vectorized
bilinear sampling instead of per-RoI CPU loops, grads via the generic vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.registry import register_op
from .nn import _batch_norm_impl, _ACTS


@register_op("lstm", diff_inputs=("Input", "Weight", "Bias", "H0", "C0"))
def lstm(ctx, op, ins):
    """operators/lstm_op.cc on padded sequences. Input [B, T, 4D]
    pre-projected gates in the reference layout (c, i, f, o)
    (math/detail/lstm_kernel.h:30 value_in/ig/fg/og); Weight [D, 4D]
    recurrent; Bias [1, 4D] (+[1, 7D] with use_peepholes: checkI/F/O)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    D = w.shape[0]
    B, T = x.shape[0], x.shape[1]
    peep = bool(op.attr("use_peepholes", True))
    bias = ins["Bias"][0].reshape(1, -1) if ins.get("Bias") else None
    if bias is not None and peep and bias.shape[1] >= 7 * D:
        b_g = bias[:, :4 * D]
        ck_i = bias[:, 4 * D:5 * D]
        ck_f = bias[:, 5 * D:6 * D]
        ck_o = bias[:, 6 * D:7 * D]
    else:
        b_g = bias if bias is not None else 0.0
        ck_i = ck_f = ck_o = 0.0
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, D), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, D), x.dtype)
    acts = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}
    act_g = acts[op.attr("gate_activation", "sigmoid")]
    act_c = acts[op.attr("candidate_activation", "tanh")]
    act_h = acts[op.attr("cell_activation", "tanh")]

    def step(carry, xt):
        h_p, c_p = carry
        g = xt + h_p @ w + b_g
        c_in = act_c(g[:, :D])
        i = act_g(g[:, D:2 * D] + c_p * ck_i)
        f = act_g(g[:, 2 * D:3 * D] + c_p * ck_f)
        c = c_in * i + c_p * f
        o = act_g(g[:, 3 * D:] + c * ck_o)
        h = o * act_h(c)
        return (h, c), (h, c)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), jnp.moveaxis(x, 1, 0))
    hidden = jnp.moveaxis(hs, 0, 1)
    cell = jnp.moveaxis(cs, 0, 1)
    if op.attr("is_reverse", False):
        hidden = hidden[:, ::-1]
        cell = cell[:, ::-1]
    return {"Hidden": hidden, "Cell": cell,
            "BatchGate": None, "BatchCellPreAct": None}


@register_op("gru", diff_inputs=("Input", "Weight", "Bias", "H0"))
def gru(ctx, op, ins):
    """operators/gru_op.cc on padded sequences: Input [B, T, 3D] gates
    (u, r, c layout per gru_unit_op.h), Weight [D, 3D], H0 [B, D]."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    D = w.shape[0]
    B = x.shape[0]
    bias = ins["Bias"][0].reshape(1, -1) if ins.get("Bias") else 0.0
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, D), x.dtype)
    acts = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}
    act_g = acts[op.attr("gate_activation", "sigmoid")]
    act_c = acts[op.attr("activation", "tanh")]
    origin = bool(op.attr("origin_mode", False))

    def step(h_p, xt):
        g = xt + bias
        ur = g[:, :2 * D] + h_p @ w[:, :2 * D]
        u = act_g(ur[:, :D])
        r = act_g(ur[:, D:])
        c = act_c(g[:, 2 * D:] + (r * h_p) @ w[:, 2 * D:])
        h = c + u * (h_p - c) if origin else u * (c - h_p) + h_p
        return h, h

    xs = jnp.moveaxis(x, 1, 0)
    if op.attr("is_reverse", False):
        xs = xs[::-1]
    _, hs = lax.scan(step, h0, xs)
    hidden = jnp.moveaxis(hs, 0, 1)
    if op.attr("is_reverse", False):
        hidden = hidden[:, ::-1]
    return {"Hidden": hidden, "BatchGate": None,
            "BatchResetHiddenPrev": None, "BatchHidden": None}


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------


def _bilinear_sample_nchw(x, py, px):
    """x [C, H, W]; py/px [...] fractional coords -> [C, ...]. Out-of-range
    samples are zero (deformable_conv_op.h DmcnIm2colBilinear)."""
    H, W = x.shape[1], x.shape[2]
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = py - y0
    wx1 = px - x0
    out = 0.0
    for dy, wy in ((0, 1 - wy1), (1, wy1)):
        for dx, wx in ((0, 1 - wx1), (1, wx1)):
            yi = y0 + dy
            xi = x0 + dx
            valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            out = out + jnp.where(valid, wy * wx, 0.0)[None] * x[:, yc, xc]
    return out


def _deformable_conv_impl(ctx, op, ins, with_mask):
    """operators/deformable_conv_op.cc (v2, modulated) / _v1: sample the
    input at offset-shifted tap positions (bilinear, zero outside), then a
    plain matmul with the filter — im2col with learned geometry."""
    x = ins["Input"][0]                          # [N, Cin, H, W]
    offset = ins["Offset"][0]                    # [N, 2*dg*kh*kw, Ho, Wo]
    w = ins["Filter"][0]                         # [Cout, Cin/g, kh, kw]
    mask = ins["Mask"][0] if (with_mask and ins.get("Mask")) else None
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    pads = [int(p) for p in op.attr("paddings", [0, 0])]
    dils = [int(d) for d in op.attr("dilations", [1, 1])]
    groups = int(op.attr("groups", 1) or 1)
    dg = int(op.attr("deformable_groups", 1) or 1)
    N, Cin, H, W = x.shape
    Cout, _, kh, kw = w.shape
    Ho = (H + 2 * pads[0] - (dils[0] * (kh - 1) + 1)) // strides[0] + 1
    Wo = (W + 2 * pads[1] - (dils[1] * (kw - 1) + 1)) // strides[1] + 1

    oy = jnp.arange(Ho) * strides[0] - pads[0]
    ox = jnp.arange(Wo) * strides[1] - pads[1]
    off = offset.reshape(N, dg, kh * kw, 2, Ho, Wo)
    cpg = Cin // dg                                # channels per deform group

    def one_image(xi, offi, maski):
        cols = []
        for g_ in range(dg):
            xg = xi[g_ * cpg:(g_ + 1) * cpg]
            taps = []
            for ki in range(kh):
                for kj in range(kw):
                    t = ki * kw + kj
                    py = (oy[:, None] + ki * dils[0]
                          + offi[g_, t, 0])                  # [Ho, Wo]
                    px = (ox[None, :] + kj * dils[1]
                          + offi[g_, t, 1])
                    s = _bilinear_sample_nchw(xg, py, px)    # [cpg, Ho, Wo]
                    if maski is not None:
                        s = s * maski[g_ * (kh * kw) + t][None]
                    taps.append(s)
            cols.append(jnp.stack(taps, axis=1))   # [cpg, kh*kw, Ho, Wo]
        return jnp.concatenate(cols, axis=0)       # [Cin, kh*kw, Ho, Wo]

    if mask is not None:
        col = jax.vmap(one_image)(x, off, mask)
    else:
        col = jax.vmap(lambda a, b: one_image(a, b, None))(x, off)
    # col [N, Cin, kh*kw, Ho, Wo] x w [Cout, Cin/g, kh, kw]
    wg = w.reshape(groups, Cout // groups, Cin // groups, kh * kw)
    colg = col.reshape(N, groups, Cin // groups, kh * kw, Ho, Wo)
    out = jnp.einsum("ngckhw,gock->ngohw", colg, wg)
    return {"Output": out.reshape(N, Cout, Ho, Wo)}


@register_op("deformable_conv", diff_inputs=("Input", "Offset", "Mask",
                                             "Filter"))
def deformable_conv(ctx, op, ins):
    return _deformable_conv_impl(ctx, op, ins, with_mask=True)


@register_op("deformable_conv_v1", diff_inputs=("Input", "Offset", "Filter"))
def deformable_conv_v1(ctx, op, ins):
    return _deformable_conv_impl(ctx, op, ins, with_mask=False)


# ---------------------------------------------------------------------------
# RoI pooling variants
# ---------------------------------------------------------------------------


@register_op("psroi_pool", diff_inputs=("X",))
def psroi_pool(ctx, op, ins):
    """operators/psroi_pool_op.cc: position-sensitive RoI average pooling —
    input channel layout [out_ch * ph * pw], each output bin averages its
    OWN channel slice over the bin region. Rois [R, 4] + RoisBatch [R]."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    out_ch = int(op.attr("output_channels"))
    ph = int(op.attr("pooled_height"))
    pw = int(op.attr("pooled_width"))
    scale = float(op.attr("spatial_scale", 1.0))
    if ins.get("RoisBatch"):
        rb = ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
    else:
        rb = jnp.zeros((rois.shape[0],), jnp.int32)
    N, C, H, W = x.shape

    hw = jnp.arange(H, dtype=jnp.float32)
    ww = jnp.arange(W, dtype=jnp.float32)

    def one(roi, b):
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = (jnp.round(roi[2]) + 1) * scale
        y2 = (jnp.round(roi[3]) + 1) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ph, rw / pw
        img = x[b]
        outs = []
        for i in range(ph):
            for j in range(pw):
                hs = jnp.floor(y1 + i * bh)
                he = jnp.ceil(y1 + (i + 1) * bh)
                ws_ = jnp.floor(x1 + j * bw)
                we = jnp.ceil(x1 + (j + 1) * bw)
                m = ((hw[:, None] >= hs) & (hw[:, None] < he)
                     & (ww[None, :] >= ws_) & (ww[None, :] < we))
                cnt = jnp.maximum(jnp.sum(m), 1.0)
                # channel slice owning this bin: [out_ch] at (i*pw+j)
                ch = img.reshape(out_ch, ph * pw, H, W)[:, i * pw + j]
                outs.append(jnp.sum(ch * m[None], axis=(1, 2)) / cnt)
        return jnp.stack(outs, 1).reshape(out_ch, ph, pw)

    return {"Out": jax.vmap(one)(rois, rb)}


@register_op("prroi_pool", diff_inputs=("X",))
def prroi_pool(ctx, op, ins):
    """operators/prroi_pool_op.cc (Precise RoI Pooling): continuous average
    of the bilinear interpolant over each bin. Computed by dense sub-pixel
    sampling (4x4 per cell span) — converges to the exact integral and
    keeps the op one fused gather/sum on device."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = int(op.attr("pooled_height"))
    pw = int(op.attr("pooled_width"))
    scale = float(op.attr("spatial_scale", 1.0))
    if ins.get("RoisBatch"):
        rb = ins["RoisBatch"][0].reshape(-1).astype(jnp.int32)
    else:
        rb = jnp.zeros((rois.shape[0],), jnp.int32)
    S = 4  # sub-samples per bin axis

    def one(roi, b):
        x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, \
            roi[2] * scale, roi[3] * scale
        bw = jnp.maximum((x2 - x1) / pw, 1e-6)
        bh = jnp.maximum((y2 - y1) / ph, 1e-6)
        iy = y1 + (jnp.arange(ph)[:, None, None, None] * bh
                   + (jnp.arange(S)[None, None, :, None] + 0.5) * bh / S)
        ix = x1 + (jnp.arange(pw)[None, :, None, None] * bw
                   + (jnp.arange(S)[None, None, None, :] + 0.5) * bw / S)
        py = jnp.broadcast_to(iy, (ph, pw, S, S))
        px = jnp.broadcast_to(ix, (ph, pw, S, S))
        s = _bilinear_sample_nchw(x[b], py, px)      # [C, ph, pw, S, S]
        return jnp.mean(s, axis=(3, 4))

    return {"Out": jax.vmap(one)(rois, rb)}


@register_op("inplace_abn", diff_inputs=("X", "Scale", "Bias"))
def inplace_abn(ctx, op, ins):
    """operators/inplace_abn_op.cc: batch norm + activation in one op (the
    in-place memory trick is XLA's job — donation/fusion)."""
    out = _batch_norm_impl(ctx, op, ins)
    act = op.attr("activation", "identity")
    if act and act not in ("identity", ""):
        if act == "leaky_relu":
            alpha = float(op.attr("alpha", 0.01))
            out["Y"] = jax.nn.leaky_relu(out["Y"], negative_slope=alpha)
        elif act == "elu":
            out["Y"] = jax.nn.elu(out["Y"], alpha=float(op.attr("alpha", 1.0)))
        else:
            out["Y"] = _ACTS[act](out["Y"])
    return out
