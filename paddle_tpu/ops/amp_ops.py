"""AMP ops — parity with reference operators/amp/
(check_finite_and_unscale / amp_check_finite_and_scale + update_loss_scaling).
bf16 is the native TPU low-precision type; loss scaling is provided for fp16
parity with the reference's dynamic-loss-scale machinery
(contrib/mixed_precision/decorator.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.registry import register_op


@register_op("amp_check_finite_and_scale", grad=None)
def amp_check_finite_and_scale(ctx, op, ins):
    xs = ins["X"]
    scale = ins["Scale"][0].reshape(())
    finite = jnp.asarray(True)
    outs = []
    for x in xs:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(x)))
    for x in xs:
        outs.append(x / scale)
    return {"Out": outs, "FoundInfinite": jnp.logical_not(finite)[None]}


@register_op("check_finite_and_unscale", grad=None)
def check_finite_and_unscale(ctx, op, ins):
    return amp_check_finite_and_scale(ctx, op, ins)


@register_op("update_loss_scaling", grad=None, is_optimizer=True)
def update_loss_scaling(ctx, op, ins):
    """Dynamic loss scaling state machine (reference
    operators/amp/update_loss_scaling_op.cc)."""
    found_inf = ins["FoundInfinite"][0].reshape(())
    prev_scale = ins["PrevLossScaling"][0].reshape(())
    good = ins["InGoodSteps"][0].reshape(())
    bad = ins["InBadSteps"][0].reshape(())
    incr_every = op.attr("incr_every_n_steps", 1000)
    decr_every = op.attr("decr_every_n_nan_or_inf", 2)
    incr_ratio = op.attr("incr_ratio", 2.0)
    decr_ratio = op.attr("decr_ratio", 0.5)

    new_bad = jnp.where(found_inf, bad + 1, 0)
    new_good = jnp.where(found_inf, 0, good + 1)
    scale_up = new_good >= incr_every
    scale_down = new_bad >= decr_every
    new_scale = jnp.where(
        scale_down, jnp.maximum(prev_scale * decr_ratio, 1.0),
        jnp.where(scale_up, prev_scale * incr_ratio, prev_scale),
    )
    new_good = jnp.where(scale_up, 0, new_good)
    new_bad = jnp.where(scale_down, 0, new_bad)

    outs = {}
    if "X" in ins:
        # zero-out grads on overflow so the optimizer step is a no-op
        outs["Out"] = [jnp.where(found_inf, jnp.zeros_like(x), x) for x in ins["X"]]
    outs.update({
        "LossScaling": new_scale[None],
        "OutGoodSteps": new_good[None].astype(jnp.int32),
        "OutBadSteps": new_bad[None].astype(jnp.int32),
    })
    return outs


@register_op("cast_with_ptr", grad=None)
def cast_with_ptr(ctx, op, ins):  # helper used by AMP rewriter
    from ..framework.core import dtype_to_jax

    return {"Out": ins["X"][0].astype(dtype_to_jax(op.attr("out_dtype")))}
