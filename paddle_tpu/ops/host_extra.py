"""Host ops, batch 2 — the reference ops whose semantics are inherently
dynamic-shape or IO-bound and that the reference itself runs CPU-side:
unique_with_counts, chunk_eval, auc, positive_negative_pair, print,
save/load/save_combine/load_combine, merge_ids/split_ids, filter_by_instag.

They execute between jitted device segments (executor host-op
segmentation); tensors cross as numpy.
"""
from __future__ import annotations

import os
import sys

import numpy as np

from ..framework.executor import register_host_op


def _np(scope, name):
    v = scope.find_var(name)
    if v is None:
        raise RuntimeError(f"host op: var {name!r} not in scope")
    return np.asarray(v)


def _set(scope, name, arr):
    import jax.numpy as jnp

    scope.set_var(name, jnp.asarray(arr))


@register_host_op("unique_with_counts")
def unique_with_counts(scope, op, exe):
    """operators/unique_with_counts_op.cc (CPU-only in the reference):
    Out = unique values in first-appearance order, Index maps X -> Out,
    Count = occurrences."""
    x = _np(scope, op.input("X")[0]).reshape(-1)
    uniq, first_idx, inverse, counts = np.unique(
        x, return_index=True, return_inverse=True, return_counts=True)
    order = np.argsort(first_idx, kind="stable")
    uniq = uniq[order]
    counts = counts[order]
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    _set(scope, op.output("Out")[0], uniq)
    _set(scope, op.output("Index")[0], remap[inverse].astype(np.int64))
    _set(scope, op.output("Count")[0], counts.astype(np.int64))


@register_host_op("print")
def print_op(scope, op, exe):
    """operators/print_op.cc: log tensor stats/values, pass through."""
    name = op.input("In")[0]
    x = _np(scope, name)
    message = op.attr("message", "")
    first_n = int(op.attr("first_n", -1))
    state = op.attrs.setdefault("__print_count__", [0])
    state[0] += 1
    if first_n < 0 or state[0] <= first_n:
        summarize = int(op.attr("summarize", 20))
        flat = x.reshape(-1)[:summarize if summarize > 0 else None]
        print(f"{message} Variable: {name}  shape: {list(x.shape)}  "
              f"dtype: {x.dtype}  data: {flat}", file=sys.stderr)
    outs = op.output("Out")
    if outs:
        _set(scope, outs[0], x)


@register_host_op("save")
def save_op(scope, op, exe):
    """operators/save_op.cc: one var in the reference tensor stream."""
    from ..framework import paddle_pb

    path = op.attr("file_path")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arr = _np(scope, op.input("X")[0])
    with open(path, "wb") as f:
        f.write(paddle_pb.tensor_to_stream(arr))


@register_host_op("load")
def load_op(scope, op, exe):
    """operators/load_op.cc."""
    from ..framework import paddle_pb

    data = open(op.attr("file_path"), "rb").read()
    arr, _, _ = paddle_pb.tensor_from_stream(data)
    _set(scope, op.output("Out")[0], arr)


@register_host_op("save_combine")
def save_combine_op(scope, op, exe):
    """operators/save_combine_op.cc: concatenated tensor streams."""
    from ..framework import paddle_pb

    path = op.attr("file_path")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        for name in op.input("X"):
            f.write(paddle_pb.tensor_to_stream(_np(scope, name)))


@register_host_op("load_combine")
def load_combine_op(scope, op, exe):
    """operators/load_combine_op.cc."""
    from ..framework import paddle_pb

    data = open(op.attr("file_path"), "rb").read()
    offset = 0
    for name in op.output("Out"):
        arr, _, offset = paddle_pb.tensor_from_stream(data, offset)
        _set(scope, name, arr)


@register_host_op("merge_ids")
def merge_ids(scope, op, exe):
    """operators/distributed_ops/merge_ids_op.cc: scatter per-shard rows
    back into the original id order (the inverse of split_ids)."""
    ids_names = op.input("Ids")
    rows_names = op.input("X")
    out_names = op.output("Out")
    all_ids = [_np(scope, n).reshape(-1) for n in ids_names]
    shard_rows = [_np(scope, n) for n in rows_names]
    n_shard = len(shard_rows)
    for ids, out_name in zip(all_ids, out_names):
        dim = shard_rows[0].shape[-1]
        out = np.zeros((len(ids), dim), shard_rows[0].dtype)
        cursor = [0] * n_shard
        # rows were produced shard-by-shard in id order
        for i, idv in enumerate(ids):
            s = int(idv) % n_shard
            out[i] = shard_rows[s][cursor[s]]
            cursor[s] += 1
        _set(scope, out_name, out)


@register_host_op("split_ids")
def split_ids(scope, op, exe):
    """operators/distributed_ops/split_ids_op.cc: route ids to shards by
    id % n_shards (dedup preserved as in reference: first occurrence)."""
    ids = np.concatenate([_np(scope, n).reshape(-1)
                          for n in op.input("Ids")])
    out_names = op.output("Out")
    n = len(out_names)
    for s, name in enumerate(out_names):
        _set(scope, name, ids[ids % n == s].reshape(-1, 1))


@register_host_op("filter_by_instag")
def filter_by_instag(scope, op, exe):
    """operators/filter_by_instag_op.cc: keep rows whose tag set intersects
    the filter tags. Padded form: Ins [N, D], Ins_tag [N, T] (0 = pad)."""
    ins_v = _np(scope, op.input("Ins")[0])
    tags = _np(scope, op.input("Ins_tag")[0])
    filter_tags = _np(scope, op.input("Filter_tag")[0]).reshape(-1)
    if tags.ndim == 1:
        tags = tags[:, None]
    keep = np.array([bool(np.intersect1d(row[row != 0], filter_tags).size)
                     for row in tags])
    idx = np.nonzero(keep)[0]
    out = ins_v[idx] if idx.size else np.zeros((1,) + ins_v.shape[1:],
                                               ins_v.dtype)
    if not idx.size and bool(op.attr("is_lod", True)):
        out = np.zeros((1,) + ins_v.shape[1:], ins_v.dtype)
    _set(scope, op.output("Out")[0], out)
    _set(scope, op.output("LossWeight")[0],
         np.ones((max(idx.size, 1), 1), np.float32)
         if idx.size else np.zeros((1, 1), np.float32))
    _set(scope, op.output("IndexMap")[0],
         np.stack([idx, idx], axis=1).astype(np.int64)
         if idx.size else np.zeros((1, 2), np.int64))


@register_host_op("auc")
def auc_op(scope, op, exe):
    """operators/metrics/auc_op.cc: streaming AUC over stat buckets.
    StatPos/StatNeg accumulate per-threshold counts across batches."""
    probs = _np(scope, op.input("Predict")[0])
    labels = _np(scope, op.input("Label")[0]).reshape(-1)
    num_thresholds = int(op.attr("num_thresholds", 4095))
    pos_name = op.input("StatPos")[0]
    neg_name = op.input("StatNeg")[0]
    stat_pos = _np(scope, pos_name).astype(np.int64).reshape(-1).copy()
    stat_neg = _np(scope, neg_name).astype(np.int64).reshape(-1).copy()
    p1 = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 \
        else probs.reshape(-1)
    idx = np.clip((p1 * num_thresholds).astype(np.int64), 0, num_thresholds)
    for i, lab in zip(idx, labels):
        if lab:
            stat_pos[i] += 1
        else:
            stat_neg[i] += 1
    tot_pos = tot_neg = 0.0
    auc = 0.0
    for i in range(num_thresholds, -1, -1):
        auc += stat_neg[i] * tot_pos + stat_pos[i] * stat_neg[i] / 2.0
        tot_pos += stat_pos[i]
        tot_neg += stat_neg[i]
    auc = auc / tot_pos / tot_neg if tot_pos and tot_neg else 0.0
    _set(scope, op.output("AUC")[0], np.asarray(auc, np.float64))
    _set(scope, op.output("StatPosOut")[0], stat_pos)
    _set(scope, op.output("StatNegOut")[0], stat_neg)


@register_host_op("positive_negative_pair")
def positive_negative_pair(scope, op, exe):
    """operators/metrics/positive_negative_pair_op.cc: ranking pair counts
    per query — (pos, neg, neutral) over same-query item pairs."""
    score = _np(scope, op.input("Score")[0]).reshape(-1)
    label = _np(scope, op.input("Label")[0]).reshape(-1)
    query = _np(scope, op.input("QueryID")[0]).reshape(-1)
    pos = neg = neu = 0.0
    for q in np.unique(query):
        sel = query == q
        s, l = score[sel], label[sel]
        for i in range(len(s)):
            for j in range(i + 1, len(s)):
                if l[i] == l[j]:
                    continue
                d = (s[i] - s[j]) * (l[i] - l[j])
                if d > 0:
                    pos += 1
                elif d < 0:
                    neg += 1
                else:
                    neu += 1
    if op.input("AccumulatePositivePair"):
        pos += float(_np(scope, op.input("AccumulatePositivePair")[0]))
        neg += float(_np(scope, op.input("AccumulateNegativePair")[0]))
        neu += float(_np(scope, op.input("AccumulateNeutralPair")[0]))
    _set(scope, op.output("PositivePair")[0], np.asarray([pos], np.float32))
    _set(scope, op.output("NegativePair")[0], np.asarray([neg], np.float32))
    _set(scope, op.output("NeutralPair")[0], np.asarray([neu], np.float32))


@register_host_op("chunk_eval")
def chunk_eval(scope, op, exe):
    """operators/metrics/chunk_eval_op.cc: chunk-level precision/recall/F1
    for sequence labeling (IOB/IOE/IOBES/plain schemes). Padded inputs
    [B, T] with SeqLength."""
    inference = _np(scope, op.input("Inference")[0])
    label = _np(scope, op.input("Label")[0])
    if inference.ndim == 1:
        inference, label = inference[None], label[None]
    lengths_in = op.input("SeqLength") if "SeqLength" in op.inputs else []
    if lengths_in:
        lengths = _np(scope, lengths_in[0]).reshape(-1)
    else:
        lengths = np.full((inference.shape[0],), inference.shape[1])
    scheme = op.attr("chunk_scheme", "IOB")
    num_chunk_types = int(op.attr("num_chunk_types"))
    excluded = set(op.attr("excluded_chunk_types", []) or [])

    def extract(seq):
        """tag id -> (type, pos) per scheme; returns set of chunks
        (start, end, type)."""
        chunks = []
        start = None
        cur_type = None
        n_pos = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
        for i, t in enumerate(list(seq) + [-1]):
            if t < 0 or t >= n_pos * num_chunk_types + (
                    1 if scheme != "plain" else 0):
                ttype, tpos = None, None
            elif scheme == "plain":
                ttype, tpos = t, "S"
            else:
                if t == n_pos * num_chunk_types:  # O tag
                    ttype, tpos = None, None
                else:
                    ttype = t // n_pos
                    p = t % n_pos
                    tpos = {"IOB": "BI", "IOE": "IE",
                            "IOBES": "BIES"}[scheme][p]
            if scheme == "plain":
                if ttype is None or (cur_type is not None
                                     and ttype != cur_type):
                    if cur_type is not None:
                        chunks.append((start, i - 1, cur_type))
                        cur_type = None
                if ttype is not None and cur_type is None:
                    start, cur_type = i, ttype
                elif ttype is not None and ttype == cur_type:
                    pass
                continue
            begins = tpos in ("B", "S") if tpos else False
            inside = tpos in ("I", "E") if tpos else False
            if cur_type is not None and (
                    ttype != cur_type or begins or tpos is None):
                chunks.append((start, i - 1, cur_type))
                cur_type = None
            if ttype is not None and cur_type is None and ttype not in excluded:
                start, cur_type = i, ttype
            if cur_type is not None and tpos in ("E", "S"):
                chunks.append((start, i, cur_type))
                cur_type = None
        return set(chunks)

    n_infer = n_label = n_correct = 0
    for b in range(inference.shape[0]):
        L = int(lengths[b])
        ic = extract(inference[b, :L])
        lc = extract(label[b, :L])
        n_infer += len(ic)
        n_label += len(lc)
        n_correct += len(ic & lc)
    precision = n_correct / n_infer if n_infer else 0.0
    recall = n_correct / n_label if n_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)
    _set(scope, op.output("Precision")[0],
         np.asarray([precision], np.float32))
    _set(scope, op.output("Recall")[0], np.asarray([recall], np.float32))
    _set(scope, op.output("F1-Score")[0], np.asarray([f1], np.float32))
    _set(scope, op.output("NumInferChunks")[0],
         np.asarray([n_infer], np.int64))
    _set(scope, op.output("NumLabelChunks")[0],
         np.asarray([n_label], np.int64))
    _set(scope, op.output("NumCorrectChunks")[0],
         np.asarray([n_correct], np.int64))


@register_host_op("assert")
def assert_op(scope, op, exe):
    """operators/assert_op.cc: fail loudly when Cond is false."""
    cond = _np(scope, op.input("Cond")[0])
    if not bool(np.all(cond)):
        parts = []
        for name in op.input("Data") or []:
            v = _np(scope, name)
            parts.append(f"{name}={v.reshape(-1)[:int(op.attr('summarize', 20))]}")
        raise AssertionError(
            "fluid.layers.Assert failed: cond is false. " + " ".join(parts))


@register_host_op("tree_conv")
def tree_conv(scope, op, exe):
    """operators/tree_conv_op.cc (TBCNN tree-based convolution) — host op:
    the patch structure is data-dependent (EdgeSet DFS, math/tree2col.cc).
    NodesVector [B, N, F]; EdgeSet [B, E, 2] 1-based (u, v) parent->child
    pairs, zero-terminated; Filter [F, 3, out_size, num_filters].
    Out [B, N, out_size, num_filters]: per root node, the depth-bounded
    patch combines node features with (eta_l, eta_r, eta_t) position
    coefficients, then one matmul with the flattened filter."""
    nodes = _np(scope, op.input("NodesVector")[0])
    edges = _np(scope, op.input("EdgeSet")[0]).astype(np.int64)
    filt = _np(scope, op.input("Filter")[0])
    max_depth = int(op.attr("max_depth", 2))
    B, N, F = nodes.shape
    _, _, out_size, num_filters = filt.shape
    W = filt.reshape(F * 3, out_size * num_filters)
    out = np.zeros((B, N, out_size, num_filters), nodes.dtype)

    for b in range(B):
        # adjacency (1-based), zero-terminated edge list
        children = {}
        node_count = 0
        for u, v in edges[b]:
            if u == 0 or v == 0:
                break
            children.setdefault(int(u), []).append(int(v))
            node_count += 1
        node_count += 1
        for root in range(1, node_count + 1):
            # DFS patch with (index, pclen, depth) per node
            patch = [(root, 1, 1, 0)]
            stack = [(root, 1, 1, 0)]
            visited = {root}
            while stack:
                node, _, _, depth = stack[-1]
                advanced = False
                kids = children.get(node, [])
                for i, v in enumerate(kids):
                    if v not in visited and depth + 1 < max_depth:
                        visited.add(v)
                        stack.append((v, i, len(kids), depth + 1))
                        patch.append((v, i + 1, len(kids), depth + 1))
                        advanced = True
                if not advanced:
                    stack.pop()
            acc = np.zeros((F, 3), nodes.dtype)
            for node, index, pclen, depth in patch:
                eta_t = (max_depth - depth) / max_depth
                tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
                eta_l = (1.0 - eta_t) * tmp
                eta_r = (1.0 - eta_t) * (1.0 - eta_l)
                feat = nodes[b, node - 1]
                acc[:, 0] += eta_l * feat
                acc[:, 1] += eta_r * feat
                acc[:, 2] += eta_t * feat
            out[b, root - 1] = (acc.reshape(-1) @ W).reshape(
                out_size, num_filters)
    _set(scope, op.output("Out")[0], out)


@register_host_op("precision_recall")
def precision_recall(scope, op, exe):
    """operators/metrics/precision_recall_op.cc:222 — multiclass streaming
    precision/recall/F1. Per-class TP/FP/TN/FN state; metrics rows are
    [macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1]."""
    ids = _np(scope, op.input("Indices")[0]).reshape(-1).astype(np.int64)
    labels = _np(scope, op.input("Labels")[0]).reshape(-1).astype(np.int64)
    cls_num = int(op.attr("class_number"))
    w = (_np(scope, op.input("Weights")[0]).reshape(-1)
         if op.input("Weights") else np.ones(len(ids), np.float32))
    TP, FP, TN, FN = 0, 1, 2, 3
    batch = np.zeros((cls_num, 4), np.float64)
    for i in range(len(ids)):
        idx, lab, wi = ids[i], labels[i], float(w[i])
        batch[:, TN] += wi
        batch[idx, TN] -= wi
        if idx == lab:
            batch[idx, TP] += wi
        else:
            batch[lab, FN] += wi
            batch[idx, FP] += wi
            batch[lab, TN] -= wi

    def metrics(states):
        def prec(tp, fp):
            return tp / (tp + fp) if tp > 0 or fp > 0 else 1.0

        def rec(tp, fn):
            return tp / (tp + fn) if tp > 0 or fn > 0 else 1.0

        def f1(p, r):
            return 2 * p * r / (p + r) if p > 0 or r > 0 else 0.0

        mp = float(np.mean([prec(s[TP], s[FP]) for s in states]))
        mr = float(np.mean([rec(s[TP], s[FN]) for s in states]))
        tot = states.sum(0)
        up = prec(tot[TP], tot[FP])
        ur = rec(tot[TP], tot[FN])
        return np.asarray([mp, mr, f1(mp, mr), up, ur, f1(up, ur)],
                          np.float64)

    accum = batch.copy()
    if op.input("StatesInfo"):
        accum += _np(scope, op.input("StatesInfo")[0]).reshape(
            cls_num, 4).astype(np.float64)
    _set(scope, op.output("BatchMetrics")[0], metrics(batch))
    _set(scope, op.output("AccumMetrics")[0], metrics(accum))
    _set(scope, op.output("AccumStatesInfo")[0], accum.astype(np.float32))


def _det_map_boxes(dets, lengths):
    """Split a flat [N,6] (label, score, x1,y1,x2,y2) by per-image counts."""
    out, s = [], 0
    for ln in lengths:
        out.append(dets[s:s + ln])
        s += ln
    return out


@register_host_op("detection_map")
def detection_map(scope, op, exe):
    """operators/detection_map_op.cc:194 — VOC mAP (integral / 11point)
    with streaming TP/FP state. DetectRes [N,6] and Label [M,6 or 5] are
    flat over the batch; per-image counts come from optional
    DetectResLength/LabelLength [B] inputs (the reference reads LoD; the
    padded convention carries lengths explicitly), defaulting to one image.
    State tensors (PosCount [C,1], TruePos/FalsePos flat [K,2] with
    TruePosLength/FalsePosLength [C]) mirror the reference's LoD layout."""
    det = _np(scope, op.input("DetectRes")[0]).reshape(-1, 6)
    lab = _np(scope, op.input("Label")[0])
    lab = lab.reshape(-1, lab.shape[-1]) if lab.size else lab.reshape(0, 6)
    class_num = int(op.attr("class_num"))
    ovt = float(op.attr("overlap_threshold", 0.5))
    eval_diff = bool(op.attr("evaluate_difficult", True))
    ap_type = str(op.attr("ap_type", "integral"))
    background = int(op.attr("background_label", 0))

    def opt_len(slot, total):
        if op.input(slot):
            return _np(scope, op.input(slot)[0]).reshape(-1).astype(int)
        return np.asarray([total])

    det_imgs = _det_map_boxes(det, opt_len("DetectResLength", len(det)))
    lab_imgs = _det_map_boxes(lab, opt_len("LabelLength", len(lab)))

    # ---- carried state ---------------------------------------------------
    pos_count = {}
    true_pos = {c: [] for c in range(class_num)}
    false_pos = {c: [] for c in range(class_num)}
    has_state = (int(_np(scope, op.input("HasState")[0]).reshape(-1)[0])
                 if op.input("HasState") else 0)
    if has_state and op.input("PosCount"):
        pc = _np(scope, op.input("PosCount")[0]).reshape(-1)
        for c in range(class_num):
            pos_count[c] = int(pc[c])
        for slot, store in (("TruePos", true_pos), ("FalsePos", false_pos)):
            flat = _np(scope, op.input(slot)[0]).reshape(-1, 2)
            lens = _np(scope, op.input(slot + "Length")[0]).reshape(-1) \
                if op.input(slot + "Length") else np.asarray([len(flat)])
            s = 0
            for c, ln in enumerate(lens.astype(int)):
                store[c] = [(float(r[0]), int(r[1])) for r in flat[s:s + ln]]
                s += ln

    def jaccard(a, b):
        a = np.clip(a, 0.0, 1.0)
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        if b[0] > a[2] or b[2] < a[0] or b[1] > a[3] or b[3] < a[1]:
            return 0.0
        inter = (ix2 - ix1) * (iy2 - iy1)
        area_a = (a[2] - a[0]) * (a[3] - a[1])
        area_b = (b[2] - b[0]) * (b[3] - b[1])
        return inter / (area_a + area_b - inter)

    # ---- this batch's TP/FP ---------------------------------------------
    for dets_n, labs_n in zip(det_imgs, lab_imgs):
        gts = {}
        for row in labs_n:
            c = int(row[0])
            if labs_n.shape[1] == 6:
                gts.setdefault(c, []).append((row[2:6], bool(row[1])))
            else:
                gts.setdefault(c, []).append((row[1:5], False))
        for c, boxes in gts.items():
            cnt = len(boxes) if eval_diff else \
                sum(1 for _, d in boxes if not d)
            if cnt:
                pos_count[c] = pos_count.get(c, 0) + cnt
        by_cls = {}
        for row in dets_n:
            by_cls.setdefault(int(row[0]), []).append(
                (float(row[1]), row[2:6]))
        for c, preds in by_cls.items():
            if c not in gts:
                for score, _ in preds:
                    true_pos[c].append((score, 0))
                    false_pos[c].append((score, 1))
                continue
            boxes = gts[c]
            visited = [False] * len(boxes)
            preds.sort(key=lambda p: -p[0])
            for score, box in preds:
                overlaps = [jaccard(box, gb) for gb, _ in boxes]
                mi = int(np.argmax(overlaps))
                if overlaps[mi] > ovt:
                    if eval_diff or not boxes[mi][1]:
                        if not visited[mi]:
                            true_pos[c].append((score, 1))
                            false_pos[c].append((score, 0))
                            visited[mi] = True
                        else:
                            true_pos[c].append((score, 0))
                            false_pos[c].append((score, 1))
                else:
                    true_pos[c].append((score, 0))
                    false_pos[c].append((score, 1))

    # ---- mAP -------------------------------------------------------------
    mAP, count = 0.0, 0
    for c, num_pos in pos_count.items():
        # the reference (detection_map_op.h:422) compares the positive
        # COUNT to background_label — an upstream quirk; skipping the
        # background CLASS is the intended semantics, and num_pos<=0
        # guards the recall division when carried state restores an
        # empty class
        if c == background or num_pos <= 0:
            continue
        if not true_pos.get(c):
            count += 1
            continue
        tp = sorted(true_pos[c], key=lambda p: -p[0])
        fp = sorted(false_pos[c], key=lambda p: -p[0])
        tp_sum = np.cumsum([v for _, v in tp])
        fp_sum = np.cumsum([v for _, v in fp])
        precision = tp_sum / np.maximum(tp_sum + fp_sum, 1e-12)
        recall = tp_sum / float(num_pos)
        if ap_type == "11point":
            maxp = np.zeros(11)
            start = len(recall) - 1
            for j in range(10, -1, -1):
                for i in range(start, -1, -1):
                    if recall[i] < j / 10.0:
                        start = i
                        if j > 0:
                            maxp[j - 1] = maxp[j]
                        break
                    elif maxp[j] < precision[i]:
                        maxp[j] = precision[i]
            mAP += float(maxp.sum() / 11)
            count += 1
        else:  # integral
            ap, prev = 0.0, 0.0
            for p, r in zip(precision, recall):
                if abs(r - prev) > 1e-6:
                    ap += p * abs(r - prev)
                prev = r
            mAP += ap
            count += 1
    if count:
        mAP /= count

    # ---- write accumulated state ----------------------------------------
    pc_out = np.zeros((class_num, 1), np.int32)
    for c, v in pos_count.items():
        if 0 <= c < class_num:
            pc_out[c, 0] = v
    _set(scope, op.output("AccumPosCount")[0], pc_out)
    for slot, store in (("AccumTruePos", true_pos),
                        ("AccumFalsePos", false_pos)):
        rows, lens = [], []
        for c in range(class_num):
            vec = store.get(c, [])
            rows.extend(vec)
            lens.append(len(vec))
        arr = (np.asarray(rows, np.float32) if rows
               else np.zeros((0, 2), np.float32))
        _set(scope, op.output(slot)[0], arr)
        if op.output(slot + "Length"):
            _set(scope, op.output(slot + "Length")[0],
                 np.asarray(lens, np.int64))
    _set(scope, op.output("MAP")[0], np.asarray(mAP, np.float32))
