"""Profiler — parity with python/paddle/fluid/profiler.py
(start_profiler/stop_profiler/profiler context, reset_profiler).

The reference has a host event profiler + CUPTI device tracer serialized to
profiler.proto with chrome-trace export (tools/timeline.py). Here the device
side is jax.profiler (XPlane, viewable in TensorBoard/Perfetto) and the host
side is a lightweight event recorder with chrome-trace export
(utils/timeline.py). stop_profiler additionally merges both sides into one
chrome trace (observability/trace_merge.py): host RecordEvents and device
spans on distinct pids, start-aligned clocks, so a single Perfetto load
shows host dispatch lined up against device execution.

Host events record the REAL thread id (async-fetch and prefetch threads get
their own trace rows instead of overdrawing on row 0), and while a device
trace is active every RecordEvent doubles as a jax.profiler.TraceAnnotation
so the same scope name appears in the XPlane capture.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

import jax

_events: List[dict] = []
_thread_names: Dict[int, str] = {}
_active = False
_device_trace_active = False
_trace_dir: Optional[str] = None
# host perf_counter (us) at the moment the device trace started — the
# shared-clock anchor for trace_merge's start alignment
_trace_host_t0_us: Optional[float] = None


def _note_thread(tid: int) -> None:
    if tid not in _thread_names:
        _thread_names[tid] = threading.current_thread().name


class RecordEvent:
    """RAII op-level host event — parity with platform::RecordEvent.

    Records the real thread id, and (while a device trace is active)
    mirrors the scope into the XPlane capture via TraceAnnotation so the
    host and device views share names.
    """

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def __enter__(self):
        if _device_trace_active:
            try:
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        # the event is recorded even when the guarded block raised — a
        # failing step must still show up in the trace, not vanish
        if _active:
            tid = threading.get_ident()
            _note_thread(tid)
            _events.append({
                "name": self.name,
                "ph": "X",
                "ts": self.t0 / 1000.0,
                "dur": (time.perf_counter_ns() - self.t0) / 1000.0,
                "pid": os.getpid(),
                "tid": tid,
            })
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
            self._ann = None


record_event = RecordEvent


def add_event(name: str, t0_ns: int, dur_ns: int, tid: Optional[int] = None):
    """Append a host event whose name is only known after it finished (e.g.
    'compile_cache/hit' vs 'compile_cache/cold' — the verdict exists once the
    first execution returns). ``tid`` defaults to the calling thread."""
    if _active:
        if tid is None:
            tid = threading.get_ident()
            _note_thread(tid)
        _events.append({
            "name": name,
            "ph": "X",
            "ts": t0_ns / 1000.0,
            "dur": dur_ns / 1000.0,
            "pid": os.getpid(),
            "tid": tid,
        })


def start_profiler(state="All", tracer_option="Default"):
    global _active, _trace_dir, _device_trace_active, _trace_host_t0_us
    _active = True
    _events.clear()
    _thread_names.clear()
    _trace_dir = os.environ.get("PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace")
    try:
        jax.profiler.start_trace(_trace_dir)
        _device_trace_active = True
    except Exception:
        # device tracing optional (e.g. second start without stop)
        _device_trace_active = False
    _trace_host_t0_us = time.perf_counter_ns() / 1000.0


_attached_program = None
_compiled_hlo_getters: dict = {}


def attach_program(program):
    """Register the program whose per-op XLA cost table should be merged
    into the chrome trace at stop_profiler (utils/op_costs.py — the
    replacement for the reference's per-op device tracer)."""
    global _attached_program
    _attached_program = program


def is_active() -> bool:
    return _active


def has_compiled(key) -> bool:
    return key in _compiled_hlo_getters


def register_compiled(key, hlo_text_getter):
    """Executor hook: while profiling, each compiled block registers a
    getter for its optimized HLO text so stop_profiler can map the
    measured device events back to IR ops (utils/device_trace.py)."""
    if _active and key not in _compiled_hlo_getters:
        _compiled_hlo_getters[key] = hlo_text_getter


def _flush_host_trace(trace_path: str) -> None:
    """Write the buffered host events (plus thread-name metadata rows) —
    isolated so the flush happens even when the optional attribution or
    merge stages below it fail."""
    meta = [{"name": "thread_name", "ph": "M", "pid": os.getpid(),
             "tid": tid, "args": {"name": name}}
            for tid, name in sorted(_thread_names.items())]
    with open(trace_path, "w") as f:
        json.dump({"traceEvents": meta + _events}, f)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    global _active, _device_trace_active
    _active = False
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass
    _device_trace_active = False
    # chrome-trace export of host events (tools/timeline.py parity) FIRST:
    # every stage after this point is optional attribution/merging, and a
    # failure there must not lose the buffered events (they were already
    # lost once, when an exception inside the profiled region skipped a
    # non-finally stop path)
    trace_path = profile_path + ".chrome_trace.json"
    try:
        _flush_host_trace(trace_path)
    except Exception as e:
        print(f"[profiler] host trace write failed: {type(e).__name__}: {e}")
    # measured per-op device attribution (reference device_tracer.cc) —
    # needs at least one compiled block to have run under the trace
    if _compiled_hlo_getters and _trace_dir:
        try:
            from .utils import device_trace

            texts = []
            for g in _compiled_hlo_getters.values():
                try:
                    texts.append(g())
                except Exception as e:   # one failed compile must not
                    print(f"[profiler] HLO text fetch failed: {e}")
            rows = device_trace.measured_op_rows(_trace_dir, texts)
            if rows:
                device_trace.merge_into_trace(rows, trace_path)
                print("[profiler] top ops by MEASURED device time:")
                device_trace.print_rows(rows, top=5)
        except Exception as e:
            print(f"[profiler] measured attribution skipped: "
                  f"{type(e).__name__}: {e}")
        _compiled_hlo_getters.clear()
    if _attached_program is not None:
        try:
            from .utils import op_costs

            rows = op_costs.program_cost_table(_attached_program)
            op_costs.merge_into_trace(rows, trace_path)
            print("[profiler] top ops by estimated device cost:")
            op_costs.print_cost_table(rows, top=10)
        except Exception as e:  # attribution is optional, like device trace
            print(f"[profiler] cost attribution skipped: "
                  f"{type(e).__name__}: {e}")
    # merged host+device chrome trace (one Perfetto load, shared clock).
    # The span-tracer ring rides along as its own plane: spans share the
    # host perf_counter clock, and spans opened BEFORE start_profiler are
    # aligned to the trace epoch inside trace_merge (not dropped).
    if _trace_dir:
        try:
            from .observability import spans as _spans
            from .observability import trace_merge

            merged = trace_merge.merge_profile(
                trace_path, _trace_dir,
                align_device_to_us=_trace_host_t0_us,
                tracer_spans=_spans.default_tracer().spans())
            if merged:
                print(f"[profiler] merged host+device trace: {merged}")
        except Exception as e:
            print(f"[profiler] host+device merge skipped: "
                  f"{type(e).__name__}: {e}")
    if sorted_key:
        _print_summary(sorted_key)


def _print_summary(sorted_key="total"):
    """Event table like the reference's profiler summary (profiler.cc
    PrintProfiler): name, calls, total/avg/min/max ms."""
    agg = {}
    for ev in _events:
        a = agg.setdefault(ev["name"], [0, 0.0, float("inf"), 0.0])
        a[0] += 1
        a[1] += ev["dur"]
        a[2] = min(a[2], ev["dur"])
        a[3] = max(a[3], ev["dur"])
    keyfn = {"calls": lambda kv: -kv[1][0], "max": lambda kv: -kv[1][3],
             "min": lambda kv: kv[1][2], "ave": lambda kv: -(kv[1][1] / kv[1][0]),
             }.get(sorted_key, lambda kv: -kv[1][1])
    print(f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
          f"{'Min(ms)':>10}{'Max(ms)':>10}")
    for name, (calls, total, mn, mx) in sorted(agg.items(), key=keyfn):
        print(f"{name:<40}{calls:>8}{total / 1e3:>12.3f}"
              f"{total / calls / 1e3:>10.3f}{mn / 1e3:>10.3f}{mx / 1e3:>10.3f}")


def reset_profiler():
    _events.clear()
    _thread_names.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    """Profiling context. ``finally`` guarantees the buffered events flush
    to the chrome trace even when the profiled region raises."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):  # name kept for API parity
    with profiler():
        yield
