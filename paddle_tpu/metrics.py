"""Host-side metrics — parity with python/paddle/fluid/metrics.py
(MetricBase, CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator,
EditDistance, Auc)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "Auc",
           "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).mean()) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no accuracy updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Streaming AUC with histogram buckets — parity with fluid.metrics.Auc /
    operators/metrics/auc_op."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        labels = np.asarray(labels).ravel()
        idx = np.clip((preds.ravel() * self._num_thresholds).astype(np.int64),
                      0, self._num_thresholds)
        for i, lbl in zip(idx, labels):
            if lbl:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (new_pos + tot_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)


class DetectionMAP:
    """fluid.metrics.DetectionMAP (metrics.py:765) — evaluator building the
    detection_map layer twice: a per-batch mAP and an accumulated mAP over
    carried TP/FP state, with reset ops clearing the state (evaluator.py
    DetectionMAP parity)."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral",
                 detect_res_length=None, label_length=None):
        from . import layers
        from .framework.program import default_main_program

        if class_num is None:
            raise ValueError("class_num is required")
        if gt_difficult is not None:
            label = layers.concat([gt_label, gt_difficult, gt_box], axis=1)
        else:
            label = layers.concat([gt_label, gt_box], axis=1)

        self.cur_map = layers.detection_map(
            input, label, class_num, background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version,
            detect_res_length=detect_res_length, label_length=label_length)

        # carried state: persistable accumulators + has_state flag
        block = default_main_program().global_block()
        self._has_state = block.create_var(
            name=f"{self.cur_map.name}.has_state", shape=[1], dtype="int32",
            persistable=True)
        pos = block.create_var(name=f"{self.cur_map.name}.pos_count",
                               shape=[class_num, 1], dtype="int32",
                               persistable=True)
        tp = block.create_var(name=f"{self.cur_map.name}.true_pos",
                              shape=[-1, 2], dtype="float32",
                              persistable=True)
        fp = block.create_var(name=f"{self.cur_map.name}.false_pos",
                              shape=[-1, 2], dtype="float32",
                              persistable=True)
        tp_len = block.create_var(name=f"{self.cur_map.name}.true_pos_len",
                                  shape=[class_num], dtype="int64",
                                  persistable=True)
        fp_len = block.create_var(name=f"{self.cur_map.name}.false_pos_len",
                                  shape=[class_num], dtype="int64",
                                  persistable=True)
        self.states = [pos, tp, fp, tp_len, fp_len]
        # has_state starts at 0 via the STARTUP program (evaluator.py
        # set_variable_initializer) — zeroing it in main would wipe the
        # carried accumulators every batch
        from .framework.program import default_startup_program

        sblock = default_startup_program().global_block()
        sblock.create_var(name=self._has_state.name, shape=[1],
                          dtype="int32", persistable=True)
        sblock.append_op(type="fill_constant", inputs={},
                         outputs={"Out": [self._has_state.name]},
                         attrs={"shape": [1], "value": 0.0, "dtype": 2})
        self.accum_map = layers.detection_map(
            input, label, class_num, background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            has_state=self._has_state, input_states=self.states,
            out_states=self.states, ap_version=ap_version,
            detect_res_length=detect_res_length, label_length=label_length)
        layers.fill_constant(shape=[1], dtype="int32", value=1,
                             out=self._has_state)

    def get_map_var(self):
        return self.cur_map, self.accum_map

    def reset(self, executor, reset_program=None):
        from . import Program, program_guard
        from . import layers

        if reset_program is None:
            reset_program = Program()
        with program_guard(reset_program):
            layers.fill_constant(shape=[1], dtype="int32", value=0,
                                 out=reset_program.global_block().create_var(
                                     name=self._has_state.name, shape=[1],
                                     dtype="int32", persistable=True))
        executor.run(reset_program)


class ChunkEvaluator(MetricBase):
    """fluid.metrics.ChunkEvaluator (metrics.py:434) — accumulate
    chunk_eval op counters; eval() -> (precision, recall, f1)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        def scalar(v):
            a = np.asarray(v).ravel()
            return int(a[0]) if a.size else 0

        self.num_infer_chunks += scalar(num_infer_chunks)
        self.num_label_chunks += scalar(num_label_chunks)
        self.num_correct_chunks += scalar(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """fluid.metrics.EditDistance (metrics.py:536) — average edit
    distance + instance error rate over batches."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances, dtype=np.float64).ravel()
        self.total_distance += float(d.sum())
        self.seq_num += int(np.asarray(seq_num).ravel()[0]
                            if np.asarray(seq_num).size else seq_num)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError(
                "There is no data in EditDistance Metric. Please check "
                "layers.edit_distance output has been added to EditDistance.")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error
