from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .geo_sgd_transpiler import GeoSgdTranspiler  # noqa: F401
