"""GEO-SGD transpiler — parity with fluid/transpiler/geo_sgd_transpiler.py +
the GeoCommunicator (operators/distributed/communicator.h Geo mode).

Semantics: trainers run the FULL local program (forward+backward+optimizer)
every step; every ``push_nums`` steps each trainer pushes the *delta* of its
params since the last sync to the pserver (server adds deltas raw —
ps_server push_delta) and pulls the merged global params back.  This trades
staleness for communication: k local steps per round-trip.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..framework.program import Program, default_main_program
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig,
                                    DistributedMode)

__all__ = ["GeoSgdTranspiler"]


def _register_geo_host_op():
    from ..framework.executor import register_host_op

    @register_host_op("geo_sgd_communicate")
    def geo_sgd_communicate(scope, op, exe):
        """Stateful host op: counts steps, and on every k-th pushes param
        deltas + pulls merged params (GeoCommunicator send/recv threads)."""
        import jax.numpy as jnp
        from ..distributed.ps_client import PSClient

        state = getattr(op, "_geo_state", None)
        if state is None:
            state = {"step": 0, "snapshots": {}}
            op._geo_state = state
        params: List[str] = op.attr("params")
        epmap: Dict[str, str] = dict(op.attr("param_ep"))
        k = int(op.attr("push_nums", 100))
        tid = int(op.attr("trainer_id", 0))
        client = PSClient.instance(tid)

        if state["step"] == 0:
            # round 0: server takes the first trainer's init; everyone pulls
            for p in params:
                local = np.asarray(scope.find_var(p))
                client.ensure_init(epmap[p], p, local)
                merged = client.pull(epmap[p], p).reshape(local.shape)
                scope.set_var(p, jnp.asarray(merged))
                state["snapshots"][p] = merged.copy()
        state["step"] += 1
        if state["step"] % k != 0:
            return
        for p in params:
            local = np.asarray(scope.find_var(p), dtype=np.float32)
            delta = local - state["snapshots"][p]
            client.push_delta(epmap[p], p, delta)
            merged = client.pull(epmap[p], p).reshape(local.shape)
            scope.set_var(p, jnp.asarray(merged))
            state["snapshots"][p] = merged.copy()


_register_geo_host_op()


class GeoSgdTranspiler(DistributeTranspiler):
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        config = config or DistributeTranspilerConfig()
        config.mode = DistributedMode.GEO
        config.sync_mode = False
        super().__init__(config)

    def _build_trainer_program(self):
        """Trainer keeps its local optimizer ops; one geo_sgd_communicate
        host op appended per step (it self-gates on push_nums)."""
        prog = self.origin_program.clone()
        block = prog.global_block()
        params = [p.name for p, _ in self.param_grad_map]
        param_ep = {p: self.param_to_ep.get(p, self.pserver_endpoints[:1])[0]
                    for p in params}
        block.append_op(
            type="geo_sgd_communicate",
            inputs={}, outputs={},
            attrs={"params": params,
                   "param_ep": list(param_ep.items()),
                   "push_nums": self.config.geo_sgd_need_push_nums,
                   "trainer_id": self.trainer_id})
        self.trainer_program = prog

    def get_pserver_program(self, endpoint: str) -> Program:
        """GEO pserver: plain SGD-free tables (deltas are added raw)."""
        prog = Program()
        block = prog.global_block()
        origin_block = self.origin_program.global_block()
        owned = {b.varname for b in self.ep_blocks.get(endpoint, [])}
        tables = []
        for name in sorted(owned):
            pvar = origin_block.var(name)
            tables.append({"name": name,
                           "shape": [int(d) for d in pvar.shape],
                           "optimizer": "sgd", "lr": 1.0,
                           "is_sparse": False})
        block.append_op(
            type="listen_and_serv",
            attrs={"endpoint": endpoint, "optimize_ops": [],
                   "owned_params": sorted(owned), "tables": tables,
                   "trainer_num": self.trainer_num, "sync_mode": False,
                   "mode": DistributedMode.GEO})
        return prog
