"""Collective program transpilers — parity with
python/paddle/fluid/transpiler/collective.py (Collective base :52,
GradAllReduce :178 which inserts scale_loss_grad + c_allreduce_sum + sync ops,
LocalSGD :270 which adds periodic parameter averaging).

The reference also injects c_gen_nccl_id/c_comm_init bootstrap ops into the
startup program; on TPU the jax.distributed coordinator replaces that
bootstrap, so the startup program is left untouched and ring_id 0 maps to the
'dp' mesh axis at lowering time (ops/collective.py).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..framework.program import Program


class Collective:
    """Base transpiler: records ring/rank wiring."""

    def __init__(self, nrings: int = 1):
        self.nrings = nrings
        self.rank = 0
        self.nranks = 1

    def transpile(self, *, startup_program: Program, main_program: Program,
                  rank: int, endpoints: List[str], current_endpoint: str,
                  wait_port: bool, params_grads=None):
        self.rank = rank
        self.nranks = max(len(endpoints), 1)
        self.startup_program = startup_program
        self.main_program = main_program
        self._transpile_startup_program()
        self._transpile_main_program(params_grads or [])

    def _transpile_startup_program(self):
        # reference: insert c_gen_nccl_id + c_comm_init per ring
        # (collective.py:117-160). TPU: coordinator bootstrap — nothing to add.
        pass

    def _transpile_main_program(self, params_grads):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Insert scale + allreduce after each gradient — collective.py:178.

    The op sequence per grad g: scale by 1/nranks (scale_loss_grad parity),
    then c_allreduce_sum on ring (grad index % nrings). Under shard_map
    lowering this is numerically identical to the reference's NCCL path.
    The nranks scaling uses the runtime axis size (so the same transpiled
    program is valid for any mesh size): c_allreduce_avg_scale op.
    """

    def _transpile_main_program(self, params_grads):
        block = self.main_program.global_block()
        grad_names = {g.name for _, g in params_grads if g is not None}
        if not grad_names:
            return
        # find the op index where each grad is last written; insert the
        # allreduce right after, before any optimizer op consumes it
        insertions: List[Tuple[int, str]] = []
        for idx, op in enumerate(block.ops):
            for name in op.output_arg_names:
                if name in grad_names:
                    insertions.append((idx, name))
        last_write = {}
        for idx, name in insertions:
            last_write[name] = idx
        # insert in descending index order to keep indices valid
        ring = 0
        for name, idx in sorted(last_write.items(), key=lambda kv: -kv[1]):
            block._insert_op(
                idx + 1,
                type="c_allreduce_avg",
                inputs={"X": [name]},
                outputs={"Out": [name]},
                attrs={"ring_id": ring % self.nrings},
            )
            ring += 1


class LocalSGD(Collective):
    """Periodic parameter averaging — collective.py:270 LocalSGD: every
    `interval` steps allreduce-mean the parameters after the local update."""

    def __init__(self, nrings: int = 1, interval: int = 1):
        super().__init__(nrings)
        self.interval = interval

    def _transpile_main_program(self, params_grads):
        block = self.main_program.global_block()
        for p, g in params_grads:
            if g is None:
                continue
            block.append_op(
                type="c_allreduce_avg",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"ring_id": 0},
            )
