"""Parameter-server transpiler — parity with
python/paddle/fluid/transpiler/distribute_transpiler.py (2,721 LoC:
DistributeTranspiler :256, transpile :544, VarBlock param slicing :80,
DistributedMode :68 sync/async/half-async/GEO).

Splits a single-process program into per-trainer and per-pserver programs:
trainer grads route to `send` ops, params come back via `recv`; each pserver
runs a `listen_and_serv` loop executing per-param optimizer blocks. Transport
on the TPU build is the host parameter service in
paddle_tpu/distributed/ (python sockets + C++ table core) instead of gRPC —
see distributed/ps_server.py.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..framework.program import Program, default_main_program
from .. import distributed as _distributed  # noqa: F401  registers host ops


class DistributedMode:
    SYNC = 0
    ASYNC = 1
    HALF_ASYNC = 2
    GEO = 3


@dataclasses.dataclass
class DistributeTranspilerConfig:
    slice_var_up: bool = True
    split_method: Optional[object] = None
    min_block_size: int = 8192
    sync_mode: bool = True
    mode: int = DistributedMode.SYNC
    geo_sgd_need_push_nums: int = 100
    runtime_split_send_recv: bool = False
    wait_port: bool = True


@dataclasses.dataclass
class VarBlock:
    """A slice of a parameter assigned to one pserver — parity with
    distribute_transpiler.py:80."""

    varname: str
    block_id: int
    offset: int
    size: int

    def __str__(self):
        return f"{self.varname}:block{self.block_id}:{self.offset}:{self.size}"


def slice_vars(params, pserver_count: int, min_block_size: int = 8192):
    """Round-robin slice params into VarBlocks across pservers
    (even split along dim 0, parity with slice_variable)."""
    blocks: List[VarBlock] = []
    for p in params:
        total = int(np.prod(p.shape)) if p.shape else 1
        if total < min_block_size * pserver_count or not p.shape:
            blocks.append(VarBlock(p.name, 0, 0, total))
            continue
        dim0 = p.shape[0]
        per = max(dim0 // pserver_count, 1)
        off = 0
        bid = 0
        row_size = total // dim0
        while off < dim0:
            rows = min(per, dim0 - off)
            blocks.append(VarBlock(p.name, bid, off * row_size, rows * row_size))
            off += rows
            bid += 1
    return blocks


class DistributeTranspiler:
    """API parity with DistributeTranspiler (:256). After transpile(), use
    get_trainer_program() / get_pserver_program(ep) / get_startup_program().
    """

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: bool = True, startup_program: Optional[Program] = None,
                  current_endpoint: str = ""):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program
        self.pserver_endpoints = [e.strip() for e in pservers.split(",") if e.strip()]

        params_grads = self._collect_param_grads()
        self.param_grad_map = params_grads
        # Params read through a lookup_table marked is_distributed live ONLY
        # on the pserver sparse table: the trainer pulls rows by id
        # (distributed_lookup_table / parameter_prefetch.cc) and pushes row
        # grads (distributed_push_sparse) instead of dense send/recv.
        self.sparse_params: Dict[str, Dict] = {}
        for op in self.origin_program.global_block().ops:
            if op.type == "lookup_table" and (op.attr("is_distributed", False)
                                              or op.attr("remote_prefetch",
                                                         False)):
                wname = op.input("W")[0]
                wvar = self.origin_program.global_block().var(wname)
                self.sparse_params[wname] = {"dim": int(wvar.shape[-1])}
        # Endpoint assignment: each param goes WHOLE to exactly one pserver,
        # greedily balanced by element count.  (The reference additionally
        # slices big params into VarBlocks across pservers —
        # distribute_transpiler.py:80 slice_variable — see slice_vars above;
        # whole-param placement keeps every table single-owner so push/pull/
        # checkpoint have one authoritative copy.)
        sizes = sorted(
            ((int(np.prod(p.shape)) if p.shape else 1, p.name)
             for p, _ in params_grads), reverse=True)
        load = {ep: 0 for ep in self.pserver_endpoints}
        self.param_to_ep: Dict[str, List[str]] = {}
        self.ep_blocks: Dict[str, List[VarBlock]] = {
            ep: [] for ep in self.pserver_endpoints}
        for size, name in sizes:
            ep = min(self.pserver_endpoints, key=lambda e: load[e])
            load[ep] += size
            self.param_to_ep[name] = [ep]
            self.ep_blocks[ep].append(VarBlock(name, 0, 0, size))
        self.var_blocks = [b for blks in self.ep_blocks.values()
                           for b in blks]
        self._build_trainer_program()
        self._transpiled = True

    # ------------------------------------------------------------------
    def _collect_param_grads(self):
        block = self.origin_program.global_block()
        pairs = []
        opt_types = {"sgd", "momentum", "adam", "adamw", "adagrad", "rmsprop",
                     "lamb", "adamax", "adadelta", "ftrl", "lars_momentum",
                     "decayed_adagrad", "dpsgd"}
        for op in block.ops:
            if op.type in opt_types:
                p = op.input("Param")[0]
                g = op.input("Grad")[0]
                pairs.append((block.var(p), block.var(g)))
        return pairs

    def _build_trainer_program(self):
        """Trainer program: forward+backward, then send grads / recv params
        instead of running optimizer ops locally."""
        prog = self.origin_program.clone()
        block = prog.global_block()
        opt_types = {"sgd", "momentum", "adam", "adamw", "adagrad", "rmsprop",
                     "lamb", "adamax", "adadelta", "ftrl", "lars_momentum",
                     "decayed_adagrad", "dpsgd"}
        # optimizer lr input per param (sent with each push so LR schedules
        # reach the server — the reference sends the lr var to the pserver
        # sub-block instead)
        self._lr_var_of = {}
        for op in block.ops:
            if op.type in opt_types:
                lr_ins = op.input("LearningRate")
                if lr_ins:
                    self._lr_var_of[op.input("Param")[0]] = lr_ins[0]
        new_ops = [op for op in block.ops if op.type not in opt_types]
        # sparse rewrite: lookup_table on a distributed param becomes a remote
        # row pull; its grad op becomes a sparse row push of Out@GRAD (the
        # dense [V, D] scatter the generic lookup_table_grad would build never
        # materializes on the trainer)
        for op in new_ops:
            if op.type == "lookup_table" and \
                    op.input("W")[0] in self.sparse_params:
                w = op.input("W")[0]
                op.type = "distributed_lookup_table"
                op.inputs = {"Ids": list(op.input("Ids"))}
                op.attrs = {"epmap": self.param_to_ep.get(
                                w, self.pserver_endpoints[:1]),
                            "table_name": w,
                            "trainer_id": self.trainer_id}
            elif op.type == "lookup_table_grad" and \
                    op.input("W") and op.input("W")[0] in self.sparse_params:
                w = op.input("W")[0]
                out_grad = op.input("Out" + "@GRAD")[0]
                op.type = "distributed_push_sparse"
                op.inputs = {"Ids": list(op.input("Ids")),
                             "Grad": [out_grad]}
                op.outputs = {}
                op.attrs = {"epmap": self.param_to_ep.get(
                                w, self.pserver_endpoints[:1]),
                            "table_name": w,
                            "trainer_id": self.trainer_id,
                            "sync_mode": self.sync_mode,
                            "lr_var": self._lr_var_of.get(w)}
        block.ops = new_ops
        prog._bump_version()
        for p, g in self.param_grad_map:
            if p.name in self.sparse_params:
                continue  # row grads already pushed by distributed_push_sparse
            eps = self.param_to_ep.get(p.name, self.pserver_endpoints[:1])
            block.append_op(
                type="send",
                inputs={"X": [g.name]},
                outputs={},
                attrs={"epmap": eps, "param": p.name,
                       "trainer_id": self.trainer_id,
                       "sync_mode": self.sync_mode,
                       "lr_var": self._lr_var_of.get(p.name),
                       "mode": self.config.mode},
            )
        if self.sync_mode:
            block.append_op(type="send_barrier", attrs={
                "endpoints": self.pserver_endpoints,
                "trainer_id": self.trainer_id})
        for p, _ in self.param_grad_map:
            if p.name in self.sparse_params:
                continue  # rows are pulled per-batch, never recv'd whole
            eps = self.param_to_ep.get(p.name, self.pserver_endpoints[:1])
            block.append_op(
                type="recv",
                inputs={},
                outputs={"Out": [p.name]},
                attrs={"epmap": eps, "param": p.name,
                       "trainer_id": self.trainer_id,
                       "mode": self.config.mode},
            )
        if self.sync_mode:
            block.append_op(type="fetch_barrier", attrs={
                "endpoints": self.pserver_endpoints,
                "trainer_id": self.trainer_id})
        self.trainer_program = prog

    # ------------------------------------------------------------------
    def get_trainer_program(self, wait_port=True) -> Program:
        assert self._transpiled
        return self.trainer_program

    def get_pserver_program(self, endpoint: str) -> Program:
        """Pserver program: one listen_and_serv op carrying the optimizer
        config for the param blocks this endpoint owns."""
        assert self._transpiled
        prog = Program()
        block = prog.global_block()
        # pserver-side optimizer: reuse the original optimizer op descs
        origin_block = self.origin_program.global_block()
        opt_descs = []
        owned = {b.varname for b in self.ep_blocks.get(endpoint, [])}
        opt_types = {"sgd", "momentum", "adam", "adamw", "adagrad", "rmsprop",
                     "lamb", "adamax", "adadelta", "ftrl", "lars_momentum",
                     "decayed_adagrad", "dpsgd"}
        # table configs: optimizer rule + shape per owned param (the server
        # side of the reference's per-param optimizer sub-blocks)
        table_opt = {"sgd": "sgd", "momentum": "momentum",
                     "lars_momentum": "momentum", "adagrad": "adagrad",
                     "adam": "adam", "adamw": "adam"}
        tables = []
        for op in origin_block.ops:
            if op.type in opt_types and op.input("Param")[0] in owned:
                opt_descs.append(op._desc_dict())
                pname = op.input("Param")[0]
                pvar = origin_block.var(pname)
                # forward the optimizer op's hyperparameters so the server-side
                # table updates with the user's values, not hardcoded defaults
                # (reference runs the actual optimizer op descs on the pserver);
                # the native table's beta1 slot doubles as momentum's mu
                hparams = {}
                if op.type in ("momentum", "lars_momentum"):
                    hparams["beta1"] = float(op.attr("mu", 0.9))
                elif op.type in ("adam", "adamw"):
                    hparams["beta1"] = float(op.attr("beta1", 0.9))
                    hparams["beta2"] = float(op.attr("beta2", 0.999))
                    hparams["eps"] = float(op.attr("epsilon", 1e-8))
                elif op.type == "adagrad":
                    hparams["eps"] = float(op.attr("epsilon", 1e-6))
                if pname in self.sparse_params:
                    tables.append({
                        "name": pname,
                        "dim": self.sparse_params[pname]["dim"],
                        "optimizer": table_opt.get(op.type, "sgd"),
                        "lr": 0.01,
                        "is_sparse": True,
                        "hparams": hparams,
                    })
                else:
                    tables.append({
                        "name": pname,
                        "shape": [int(d) for d in pvar.shape],
                        "optimizer": table_opt.get(op.type, "sgd"),
                        "lr": 0.01,  # overwritten per push by the trainer's lr
                        "is_sparse": False,
                        "hparams": hparams,
                    })
        block.append_op(
            type="listen_and_serv",
            attrs={
                "endpoint": endpoint,
                "optimize_ops": opt_descs,
                "owned_params": sorted(owned),
                "tables": tables,
                "trainer_num": self.trainer_num,
                "sync_mode": self.sync_mode,
                "mode": self.config.mode,
            },
        )
        return prog

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint), self.get_startup_program(endpoint)

    def get_startup_program(self, endpoint: str = "",
                            pserver_program: Optional[Program] = None) -> Program:
        """Pserver startup: initialize owned param blocks (from the trainer's
        startup values pushed at init, so an empty program here)."""
        return Program()
