"""Program desc (de)serialization.

Capability parity with the reference's protobuf ProgramDesc persistence
(framework/framework.proto + program_desc.cc): the full IR round-trips through
a JSON-able dict so save_inference_model / fluid.io.save artifacts are
self-contained. (The reference uses protobuf binary; the format here is JSON —
same information content, versioned.)
"""
from __future__ import annotations

from typing import Dict

from .core import VarType
from .program import Block, Operator, Parameter, Program, Variable


def program_to_desc(program: Program) -> Dict:
    return program._desc_dict()


def program_from_desc(desc: Dict) -> Program:
    program = Program.__new__(Program)
    program.blocks = []
    program.current_block_idx = 0
    program.random_seed = desc.get("random_seed", 0)
    program._seed_counter = 0
    program._is_start_up_program = False
    program._pass_applied = []
    program._annotations = dict(desc.get("annotations", {}))
    for bdesc in desc["blocks"]:
        blk = Block(program, bdesc["idx"], bdesc.get("parent_idx", -1))
        blk.forward_block_idx = bdesc.get("forward_block_idx", -1)
        program.blocks.append(blk)
    for bdesc, blk in zip(desc["blocks"], program.blocks):
        params = set(bdesc.get("params", []))
        for vdesc in bdesc["vars"]:
            if vdesc["name"] in params:
                var = Parameter(
                    blk, shape=vdesc["shape"], dtype=vdesc["dtype"],
                    name=vdesc["name"],
                )
                var.stop_gradient = vdesc.get("stop_gradient", False)
            else:
                var = Variable(
                    blk,
                    name=vdesc["name"],
                    shape=vdesc["shape"],
                    dtype=vdesc["dtype"],
                    type=VarType(vdesc.get("type", int(VarType.LOD_TENSOR))),
                    persistable=vdesc.get("persistable", False),
                    stop_gradient=vdesc.get("stop_gradient", False),
                    is_data=vdesc.get("is_data", False),
                )
            if vdesc.get("sharding") is not None:
                from ..sharding.spec import spec_from_json

                var.sharding = spec_from_json(vdesc["sharding"])
            blk.vars[var.name] = var
        for odesc in bdesc["ops"]:
            op = Operator(
                blk,
                type=odesc["type"],
                inputs=odesc.get("inputs", {}),
                outputs=odesc.get("outputs", {}),
                attrs=odesc.get("attrs", {}),
            )
            blk.ops.append(op)
    return program
