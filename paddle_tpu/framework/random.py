"""paddle.framework.random — parity with python/paddle/framework/random.py
(manual_seed).

Seeds both static programs (Program.random_seed feeds the executor's rng
stream) and the dygraph eager rng stream.
"""
from __future__ import annotations

from .program import default_main_program, default_startup_program

__all__ = ["manual_seed"]


def manual_seed(seed: int) -> None:
    seed = int(seed)
    default_main_program().random_seed = seed
    default_startup_program().random_seed = seed
    from ..tensor._dispatch import reset_eager_seed
    reset_eager_seed(seed)
