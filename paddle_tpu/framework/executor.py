"""Executor: compiles whole Blocks to single XLA computations.

The reference Executor (paddle/fluid/framework/executor.cc:432-494) is a per-op
interpreter: the hot loop calls op->Run per OpDesc with per-op kernel dispatch.
Here the SAME user API (``Executor.run(program, feed, fetch_list)`` — python
surface parity with fluid/executor.py:890) instead lowers the whole Block to one
jit-compiled JAX function per (program-fingerprint, feed-signature): forward,
backward and optimizer update fuse into one XLA module, parameters are donated
(buffer reuse ≙ the reference's inplace/memory passes for free).
"""
from __future__ import annotations

import logging
import os
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import (Place, XLAPlace, compile_cache_counters, dtype_to_jax,
                   ensure_compile_cache, get_flag)
from .program import Program, Variable, default_main_program
from .registry import LowerCtx, run_lowering, get_op_spec, has_op

logger = logging.getLogger("paddle_tpu.executor")

# ---------------------------------------------------------------------------
# Always-live metrics (observability/metrics.py). Children are resolved ONCE
# at import so the steady-state cost is a float add — unlike RecordEvents,
# these exist whether or not a profiling session is active (the "profiling
# started after the first step" dropped-compile-events satellite).
# ---------------------------------------------------------------------------
from ..observability import flight as _flight
from ..observability import goodput as _goodput
from ..observability import metrics as _obs_metrics
from ..observability import spans as _spans

_OBS = _obs_metrics.default_registry()
# the wall-clock ledger (docs/observability.md "Goodput & tracing"): run/
# train paths bracket themselves in exclusive-time category timers so the
# goodput report can attribute every second of a run
_gp = _goodput.ledger()
_m_dispatch = _OBS.counter(
    "paddle_executor_dispatch_total",
    "Executor.run dispatches by path (fast = dispatch-record hit)",
    ("path",))
_m_dispatch_fast = _m_dispatch.labels("fast")
_m_dispatch_slow = _m_dispatch.labels("slow")
_m_compile = _OBS.counter(
    "paddle_executor_compile_total",
    "Compiled (program, feed-sig, fetch) blocks built")
_m_compile_ms = _OBS.histogram(
    "paddle_executor_compile_ms",
    "Block build+trace wall time (ms); the XLA compile itself is lazy")
_m_compile_cache = _OBS.counter(
    "paddle_compile_cache_total",
    "Persistent XLA compile cache outcomes", ("verdict",))
_m_run_ms = _OBS.histogram(
    "paddle_executor_run_ms",
    "Executor.run host wall time per call (async dispatch, ms)")
_m_device_wait_ms = _OBS.histogram(
    "paddle_executor_device_wait_ms",
    "Blocking device->host fetch materialization time per run (ms)")
_m_fetch_stall = _OBS.counter(
    "paddle_fetch_sync_stall_ms_total",
    "train_from_dataset fetch-sync stall time at print/final boundaries (ms)")

# streaming datasets ride their batch-aligned resume token on each feed
# under this key (dataset.streaming.StreamingDataset.STATE_KEY); the
# dataset loop pops it before dispatch and serializes it into the elastic
# checkpoint's data_state
_STREAM_STATE_KEY = "__stream_state__"

_prof_mod = None


def _prof():
    """The profiler module, imported lazily once (avoids the package-init
    cycle) and cached so the steady-state path pays a global read, not an
    import-machinery lookup."""
    global _prof_mod
    if _prof_mod is None:
        from .. import profiler

        _prof_mod = profiler
    return _prof_mod


_health_mod = None


def _health():
    """The in-run health module (parallel/health.py), lazily cached like
    :func:`_prof`.  ``progress()`` stamps from the dispatch paths feed the
    hang watchdog — a single global read + None check until a watchdog is
    installed, so the fast path stays inside the dispatch-overhead gate."""
    global _health_mod
    if _health_mod is None:
        from ..parallel import health

        _health_mod = health
    return _health_mod


class Scope:
    """Host-side name -> device array map — parity with framework/scope.h:46.

    The reference Scope is a hierarchical C++ name->Variable table; here
    variables are jax.Arrays living in HBM, and the hierarchy collapses to
    parent chaining for sub-scopes (used by control flow at lowering time).
    """

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self.parent = parent

    def var(self, name: str):
        return self._vars.setdefault(name, None)

    def set_var(self, name: str, value):
        self._vars[name] = value

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def erase(self, names: Sequence[str]):
        for n in names:
            self._vars.pop(n, None)

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)


_scope_stack: List[Scope] = [Scope()]


def global_scope() -> Scope:
    return _scope_stack[-1]


class scope_guard:
    """fluid.executor.scope_guard parity: swap the ambient global scope so
    io/save/load and Executor.run default into ``scope``."""

    def __init__(self, scope: Scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()


import dataclasses


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """How a program maps onto a device mesh."""

    mode: str = "single"  # single | gspmd | shard_map
    axes: Tuple[Tuple[str, int], ...] = ()
    data_axis: Optional[str] = None
    # ring_id -> axis name (collective ops lower over these)
    ring_axes: Any = dataclasses.field(default_factory=dict)

    def signature(self):
        return (self.mode, self.axes, self.data_axis,
                tuple(sorted(self.ring_axes.items())) if self.ring_axes else ())


# weakref-keyed: entries die with their Program instead of pinning up to
# 4096 dead programs/executables; the compiled object is held by weakref and
# validated by identity on lookup so id() reuse can't alias entries
_plan_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def plan_for_program(program: Program, compiled=None) -> Optional[MeshPlan]:
    """Derive the mesh plan from CompiledProgram state / program annotations.
    Memoized per (program, compiled identity, version) — Executor.run calls
    this once per step."""
    version = program._version_token()
    sub = _plan_cache.get(program)
    if sub is not None:
        hit = sub.get(version)
        if hit is not None:
            cref, cached_plan = hit
            if cref is None:
                if compiled is None:
                    return cached_plan
            else:
                # a dead weakref must NOT match compiled=None — the cached
                # plan belonged to a (now GC'd) CompiledProgram, while a plain
                # run must re-derive from program annotations
                target = cref()
                if target is not None and target is compiled:
                    return cached_plan

    plan: Optional[MeshPlan] = None
    ann = program._annotations
    if compiled is not None and compiled._is_data_parallel:
        ring_axes = dict(compiled._mesh_axes)
        has_collectives = any(
            op.type.startswith("c_")
            or op.type in ("allreduce", "broadcast", "dgc_momentum",
                           "sync_batch_norm", "sync_batch_norm_grad")
            for op in program.global_block().ops
        )
        mode = "shard_map" if has_collectives else "gspmd"
        dp_size = len(compiled._places) if compiled._places else -1
        plan = MeshPlan(mode=mode, axes=(("dp", dp_size),), data_axis="dp",
                        ring_axes=ring_axes or {0: "dp"})
    elif "mesh" in ann:
        m = ann["mesh"]
        plan = MeshPlan(
            mode=m.get("mode", "gspmd"),
            axes=tuple(tuple(a) for a in m.get("axes", ())),
            data_axis=m.get("data_axis"),
            ring_axes=dict(m.get("ring_axes", {})),
        )
    sub = _plan_cache.setdefault(program, {})
    if len(sub) > 64:  # bound per-program version history
        sub.clear()
    sub[version] = (weakref.ref(compiled) if compiled is not None else None,
                    plan)
    return plan


class _CompiledBlock:
    """One jit-compiled executable for (program, feed signature, fetch list).

    Three execution modes replace the reference's executor zoo
    (Executor / ParallelExecutor+SSA graph / NCCL rings):
      - single: one device, plain jit.
      - gspmd:  a jax.sharding.Mesh + NamedShardings on params/feeds; XLA's
        partitioner inserts gradient all-reduces etc. (subsumes
        ParallelExecutor's AllReduceOpHandle graph, details/build_strategy).
      - shard_map: per-rank program semantics for Fleet-transpiled programs
        that carry explicit c_allreduce_*/c_broadcast ops (ring_id -> mesh
        axis); matches the reference's collective-op execution model exactly.
    """

    def __init__(self, program: Program, feed_sig, fetch_names, param_names,
                 written_names, mesh_plan=None, donate: bool = True,
                 scope: Optional["Scope"] = None, report_name: str = ""):
        self.program = program
        self.feed_names = [n for n, _, _ in feed_sig]
        self.fetch_names = list(fetch_names)
        self.param_names = list(param_names)
        self.written_names = list(written_names)
        self.mesh_plan = mesh_plan
        self.report_name = report_name or (
            f"{fetch_names[0] if fetch_names else 'main'}"
            f"#{len(program.global_block().ops)}ops")
        # hang-watchdog progress site (docs/health.md): collective-carrying
        # shard_map blocks get their own label so paddle_hangs_total{site}
        # points at the comm path when a mismatched collective wedges
        self.progress_site = ("collective/shard_map"
                              if mesh_plan is not None
                              and mesh_plan.mode == "shard_map"
                              else "executor.run")
        # AOT compile state: the first call lowers + compiles explicitly and
        # keeps BOTH handles, so the executable that runs every step is the
        # same object that serves .as_text() for the profiler and
        # cost/memory analysis for the program report — no re-compile for
        # introspection (the old _hlo_text_getter paid a fresh
        # lower().compile() per block just for HLO text).
        self._executable = None
        self._aot_failed = False
        self.compile_ms: Optional[float] = None
        self.cache_verdict: Optional[str] = None
        self.report: Optional[Dict[str, Any]] = None
        self._in_summary = None
        mesh_axes = (mesh_plan.ring_axes if mesh_plan else {})
        block = program.global_block()
        written = set(written_names)
        # steady-state split, computed once instead of per __call__
        self._mutable_names = [n for n in self.param_names if n in written]
        self._const_names = [n for n in self.param_names if n not in written]
        # fetches that alias donated state: a fetch of a written persistable
        # may share its buffer with the new_state output, and the NEXT step
        # donates that scope array — an async (return_numpy=False) caller
        # would then hold a deleted buffer. These indices get a defensive
        # device-side copy after each call.
        self._fetch_copy_idx = [i for i, n in enumerate(self.fetch_names)
                                if n in written]
        # set during the first trace: did any lowering consume an rng key?
        self._rng_consumed = False

        def fn(mutable_params: Dict[str, Any], const_params: Dict[str, Any],
               feeds: Dict[str, Any], rng_key):
            env: Dict[str, Any] = {}
            env.update(const_params)
            env.update(mutable_params)
            env.update(feeds)
            rng_uses_before = LowerCtx.rng_use_count
            ctx = LowerCtx(program, block, env, rng_key=rng_key,
                           mesh_axes=mesh_axes)
            for op in block.ops:
                run_lowering(ctx, op)
            if LowerCtx.rng_use_count != rng_uses_before:
                self._rng_consumed = True
            fetches = [env[n] for n in self.fetch_names]
            # a declared persistable output may legitimately stay unbound
            # (bootstrap no-op lowerings, @EMPTY@ grads) — tolerate it
            new_state = {n: env[n] for n in self.written_names if n in env}
            return fetches, new_state

        donate_args = (0,) if donate else ()

        if mesh_plan is None or mesh_plan.mode == "single":
            self._jitted = jax.jit(fn, donate_argnums=donate_args)
            self.mesh = None
            return

        from ..parallel.mesh import build_mesh, named_sharding

        mesh = build_mesh(mesh_plan.axes)
        self.mesh = mesh
        n_dev = int(np.prod(mesh.devices.shape))
        data_axis = mesh_plan.data_axis
        block_vars = block.vars

        def param_spec(name):
            var = block_vars.get(name)
            return getattr(var, "sharding", None) if var is not None else None

        def feed_dims(shape):
            """Shard the batch (dim 0) only when it divides the mesh evenly;
            small feeds (lr tensors, flags) stay replicated."""
            if shape and shape[0] % n_dev == 0 and shape[0] > 0:
                return (data_axis,) + (None,) * (len(shape) - 1)
            return None

        if mesh_plan.mode == "gspmd":
            mutable_sh = {n: named_sharding(mesh, param_spec(n))
                          for n in self.param_names if n in written}
            const_sh = {n: named_sharding(mesh, param_spec(n))
                        for n in self.param_names if n not in written}
            # annotated feeds (sharding propagation, paddle_tpu/sharding/)
            # use their propagated spec; unannotated ones keep the
            # batch-dim heuristic
            feed_sh = {n: named_sharding(
                mesh, param_spec(n) if param_spec(n) is not None
                else feed_dims(shape))
                for n, shape, _ in feed_sig}
            rng_sh = named_sharding(mesh, None)
            self._jitted = jax.jit(
                fn,
                in_shardings=(mutable_sh, const_sh, feed_sh, rng_sh),
                donate_argnums=donate_args,
            )
            return

        # shard_map mode: per-rank execution, explicit collectives in program.
        # Fetches are concatenated along dim 0 across ranks — parity with
        # ParallelExecutor's fetch merge (a fetched scalar loss comes back as
        # one value per device, exactly like the reference).
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import (aval_of, feed_aval, jit_shard_map,
                                     probe_produced_state)

        # discover which written names are actually produced (abstract-eval
        # probe, so the shard_map out_specs pytree is known before tracing)
        mutable_avals = {n: aval_of(scope.find_var(n)) for n in self.param_names
                         if n in written and scope is not None and scope.has_var(n)}
        const_avals = {n: aval_of(scope.find_var(n)) for n in self.param_names
                       if n not in written and scope is not None and scope.has_var(n)}
        feed_avals = {n: feed_aval(shape, dt) for n, shape, dt in feed_sig}
        produced = probe_produced_state(fn, mutable_avals, const_avals,
                                        feed_avals, self.written_names)
        self._produced_state = produced

        def per_rank(mutable_params, const_params, feeds, rng_key):
            fetches, new_state = fn(mutable_params, const_params, feeds, rng_key)
            fetches = [jnp.atleast_1d(f) for f in fetches]
            new_state = {n: new_state[n] for n in produced}
            return fetches, new_state

        mutable_specs = {n: P() for n in self.param_names if n in written}
        const_specs = {n: P() for n in self.param_names if n not in written}
        feed_specs = {
            n: P(*fd) if (fd := feed_dims(shape)) else P()
            for n, shape, _ in feed_sig
        }
        fetch_specs = [P(data_axis) for _ in fetch_names]
        state_specs = {n: P() for n in produced}

        self._jitted = jit_shard_map(
            per_rank, mesh,
            in_specs=(mutable_specs, const_specs, feed_specs, P()),
            out_specs=(fetch_specs, state_specs),
            donate_argnums=donate_args)

    def _hlo_text_getter(self, *call_args):
        """Deferred optimized-HLO-text fetch for profiler attribution.
        Abstracts the args immediately (shape/dtype only) so the getter
        stays valid after donation invalidates the live buffers."""
        import jax

        def absify(x):
            v = getattr(x, "value", x)
            return jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v))

        avals = jax.tree.map(absify, call_args)
        jitted = self._jitted

        def getter():
            # the steady-state executable IS the AOT-compiled object, so
            # HLO text is a free read off it; the fresh lower().compile()
            # survives only as the fallback for blocks where AOT dispatch
            # was unavailable (self._aot_failed).
            if self._executable is not None:
                return self._executable.as_text()
            return jitted.lower(*avals).compile().as_text()

        return getter

    # -- explicit AOT compile: one compile serves dispatch + introspection --
    def _aot_compile(self, mutable, const, feeds, rng_key) -> None:
        """Lower + compile the block explicitly and keep the executable.
        On any failure the block permanently falls back to implicit jit
        dispatch (AOT is an optimization + introspection surface, never a
        correctness requirement)."""
        watch = bool(get_flag("FLAGS_compile_cache_dir"))
        if watch:
            h0, m0 = compile_cache_counters()
        t0 = time.perf_counter_ns()
        try:
            # a first-call XLA compile can legitimately run for minutes:
            # pause the hang-watchdog clock for its duration, and charge
            # the wall time to the ledger's compile category
            with _health().suspend(), _gp.timer("compile"), \
                    _spans.span(f"compile/{self.report_name}"):
                lowered = self._jitted.lower(mutable, const, feeds, rng_key)
                executable = lowered.compile()
        except Exception as e:
            self._aot_failed = True
            logger.info("AOT compile unavailable for %s (%s: %s); "
                        "falling back to implicit jit dispatch",
                        self.report_name, type(e).__name__, e)
            return
        self.compile_ms = (time.perf_counter_ns() - t0) / 1e6
        if watch:
            h1, m1 = compile_cache_counters()
            self.cache_verdict = ("hit" if h1 > h0
                                  else "cold" if m1 > m0 else None)
        self._executable = executable
        # input avals summarized BEFORE the first call: donation will
        # invalidate the mutable buffers
        from ..observability import program_report as _prep

        self._in_summary = _prep._aval_rows((mutable, const, feeds))

    def _publish_report(self, fetches, new_state) -> None:
        """Emit the per-executable program report (once, after the first
        successful call so output avals are real)."""
        from ..observability import program_report as _prep

        self.report = _prep.capture(
            self.report_name,
            compiled=self._executable,
            compile_ms=self.compile_ms,
            cache=self.cache_verdict,
            donated=list(self._mutable_names),
            inputs=self._in_summary,
            outputs=(fetches, new_state),
            extra={
                "mode": self.mesh_plan.mode if self.mesh_plan else "single",
                "nops": len(self.program.global_block().ops),
                "feeds": list(self.feed_names),
                "fetches": list(self.fetch_names),
            })
        self._in_summary = None

    def __call__(self, scope: Scope, feed: Dict[str, Any], rng_key):
        feeds = {n: feed[n] for n in self.feed_names}
        return self.fast_call(scope, feeds, rng_key)

    def fast_call(self, scope: Scope, feeds: Dict[str, Any], rng_key):
        """Steady-state entry: ``feeds`` must already contain exactly
        ``feed_names`` (the dispatch record guarantees it)."""
        _health().progress(self.progress_site)
        find = scope.find_var
        mutable = {}
        const = {}
        for n in self._mutable_names:  # persistables read from scope
            v = find(n)
            if v is None:
                raise RuntimeError(
                    f"persistable var {n!r} is not initialized in scope — "
                    "run the startup program first"
                )
            mutable[n] = v  # donated: updated in place on device
        for n in self._const_names:
            v = find(n)
            if v is None:
                raise RuntimeError(
                    f"persistable var {n!r} is not initialized in scope — "
                    "run the startup program first"
                )
            const[n] = v
        prof = _prof()
        if prof.is_active():
            # owned token, not id(self): a GC'd block's reused address
            # would silently suppress registration of a new block
            key = self.__dict__.setdefault("_profile_key", object())
            if not prof.has_compiled(key):
                # capture avals BEFORE the call: mutable buffers are donated
                prof.register_compiled(
                    key, self._hlo_text_getter(mutable, const, feeds,
                                               rng_key))
        first_aot = False
        if self._executable is None and not self._aot_failed:
            self._aot_compile(mutable, const, feeds, rng_key)
            first_aot = self._executable is not None
        if self._executable is not None:
            try:
                fetches, new_state = self._executable(mutable, const, feeds,
                                                      rng_key)
            except TypeError as e:
                # signature drift the AOT call can't absorb (raised during
                # argument processing, before execution — no buffer was
                # donated yet); fall back to implicit jit for good
                logger.info("AOT dispatch mismatch for %s (%s); reverting "
                            "to jit dispatch", self.report_name, e)
                self._executable = None
                self._aot_failed = True
                first_aot = False
                fetches, new_state = self._jitted(mutable, const, feeds,
                                                  rng_key)
        else:
            fetches, new_state = self._jitted(mutable, const, feeds, rng_key)
        if first_aot:
            self._publish_report(fetches, new_state)
        for n, v in new_state.items():
            scope.set_var(n, v)
        for i in self._fetch_copy_idx:
            # detach written-persistable fetches from the donated state
            # buffer (async dispatch; no host sync)
            fetches[i] = jnp.copy(fetches[i])
        return fetches


# ---------------------------------------------------------------------------
# Host ops: ops that run Python-side between jitted device segments (the
# reference's RPC/PS ops — send/recv/listen_and_serv — execute on the host
# inside its per-op interpreter; here the Executor splits the block at host
# ops and jits the device spans around them).
# ---------------------------------------------------------------------------

_HOST_OPS: Dict[str, Any] = {}


def register_host_op(op_type: str):
    def deco(fn):
        _HOST_OPS[op_type] = fn
        return fn
    return deco


def is_host_op_type(t: str) -> bool:
    return t in _HOST_OPS


_FAST_MISS = object()


class _DispatchRecord:
    """Steady-state dispatch record for one (program, feed-sig, fetch) combo.

    ``Executor.run`` pays a per-step Python tax on the slow path: feed dict
    sort, ``np.asarray`` per feed, cache-key rebuild, host-op scan, mesh-plan
    lookup. After the first step all of that is invariant, so the record
    pins the compiled block plus a prebuilt feed flattener and the run goes
    straight from the user's feed dict to the jitted call. Any mismatch
    (program mutated, feed shape/dtype drift, flags) falls back to the full
    path, which re-derives and replaces the record.
    """

    __slots__ = ("key_obj", "compiled", "dp_flag", "program", "version",
                 "seed", "exe", "feed_checks", "nfeeds", "rng_base",
                 "rng_used")

    def __init__(self, key_obj, compiled, program, exe, feed_sig, raw_dtypes):
        self.key_obj = key_obj
        self.compiled = compiled
        self.dp_flag = (compiled._is_data_parallel
                        if compiled is not None else None)
        self.program = program
        self.version = program._version_token()
        self.seed = program.random_seed
        self.exe = exe
        self.rng_used = exe._rng_consumed
        # rng-free programs reuse one key; rng programs fold the step in,
        # bit-identical to the slow path's fold_in(PRNGKey(seed), step)
        self.rng_base = jax.random.PRNGKey(self.seed or 0)
        checks = []
        for name, shape, dt in feed_sig:
            # accept the normalized dtype and its x64-narrowed compute dtype
            # (a device-prefetched int64 feed arrives as int32)
            accepted = frozenset({dt, str(dtype_to_jax(dt))})
            raw = raw_dtypes.get(name)
            cast = None
            if raw is not None and raw not in accepted:
                cast = jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt)
            checks.append((name, shape, accepted, raw, cast))
        self.feed_checks = checks
        self.nfeeds = len(checks)

    def prepare(self, feed: Dict[str, Any]):
        """Validate + flatten the user's feed dict against the recorded
        signature. Returns the dict to pass to the jitted call, or None when
        the feed doesn't match (caller falls back to the full path)."""
        if len(feed) != self.nfeeds:
            return None
        out = feed
        for name, shape, accepted, raw, cast in self.feed_checks:
            v = feed.get(name)
            if v is None or getattr(v, "shape", None) != shape:
                return None
            dt = str(getattr(v, "dtype", ""))
            if dt in accepted:
                continue
            if dt == raw and cast is not None:
                # same raw dtype as at record build: prebuilt cast (e.g. the
                # user feeds float64 into a float32 var every step)
                if out is feed:
                    out = dict(feed)
                out[name] = np.asarray(v).astype(cast)
            else:
                return None
        return out


class Executor:
    """User-facing executor — API parity with fluid/executor.py:890 Executor.run."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or XLAPlace(0)
        self._cache: Dict[Tuple, _CompiledBlock] = {}
        self._view_cache: Dict[Tuple, Program] = {}
        self._dispatch_records: Dict[Tuple, _DispatchRecord] = {}
        # per-program compile-signature history: the recompile explainer
        # diffs a fresh build against these siblings to name the cause
        self._compile_history: Dict[int, List[dict]] = {}
        # FLAGS_check_program: program versions already statically verified
        self._checked_programs: set = set()
        self._fast_hits = 0
        self._step = 0

    def close(self):
        self._cache.clear()
        self._dispatch_records.clear()
        self._compile_history.clear()
        self._checked_programs.clear()

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        # the whole call is step wall-time; nested timers re-bucket the
        # compile / device-wait shares out of it (exclusive accounting)
        with _gp.timer("productive_step"):
            return self._run_impl(program, feed, fetch_list, feed_var_name,
                                  fetch_var_name, scope, return_numpy,
                                  use_program_cache)

    def _run_impl(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        from .compiler import CompiledProgram

        fetch_names = [
            v.name if isinstance(v, Variable) else str(v) for v in (fetch_list or [])
        ]

        # ---- steady-state fast path: dispatch record hit ----------------
        if (self._dispatch_records and use_program_cache
                and (feed is None or type(feed) is dict)
                and get_flag("FLAGS_dispatch_fast_path")
                and not get_flag("FLAGS_check_nan_inf")):
            pkey = (id(program) if program is not None
                    else id(default_main_program()))
            rec = self._dispatch_records.get((pkey, tuple(fetch_names)))
            if rec is not None:
                out = self._try_fast_run(rec, feed if feed else {}, scope,
                                         return_numpy)
                if out is not _FAST_MISS:
                    return out

        compiled = None
        if isinstance(program, CompiledProgram):
            compiled = program
            program = compiled.program
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        feed = dict(feed or {})

        if get_flag("FLAGS_check_program"):
            self._check_program(program, feed, fetch_names)

        if any(op.type in _HOST_OPS for op in program.global_block().ops):
            return self._run_with_host_ops(
                program, feed, fetch_names, scope, return_numpy)

        if (get_flag("FLAGS_check_nan_inf")
                and get_flag("FLAGS_check_nan_inf_level") == "op"):
            return self._run_op_level_checked(
                program, feed, fetch_names, scope, return_numpy)

        # normalize feed values to jax arrays (device put happens inside jit)
        feed_arrays: Dict[str, Any] = {}
        feed_sig = []
        raw_dtypes: Dict[str, Optional[str]] = {}
        for name, value in sorted(feed.items()):
            raw_dtypes[name] = (str(value.dtype)
                                if isinstance(value, np.ndarray) else None)
            arr = _normalize_feed(program.global_block().vars.get(name),
                                  value)
            feed_arrays[name] = arr
            feed_sig.append((name, tuple(arr.shape), str(arr.dtype)))

        mesh_plan = plan_for_program(program, compiled)
        key = (
            id(program),
            program._version_token(),
            tuple(feed_sig),
            tuple(fetch_names),
            mesh_plan.signature() if mesh_plan else None,
        )
        prof = _prof()
        exe = self._cache.get(key)
        newly_built = exe is None
        if exe is None:
            block = program.global_block()
            param_names, written = _analyze_persistables(program)
            ensure_compile_cache()
            _m_compile.inc()
            report_name = str(
                program._annotations.get("report_name")
                or f"{fetch_names[0] if fetch_names else 'main'}"
                   f"#{len(block.ops)}ops")
            self._explain_rebuild(program, report_name, feed_sig,
                                  fetch_names, mesh_plan)
            with _m_compile_ms.time(), _gp.timer("compile"), \
                    prof.RecordEvent(f"compile/{len(block.ops)}ops"):
                if "pipeline" in program._annotations:
                    from ..parallel.pipeline_program import (
                        _CompiledPipelineBlock)
                    exe = _CompiledPipelineBlock(
                        program, feed_sig, fetch_names, param_names,
                        written, scope=scope, mesh_plan=mesh_plan)
                elif "grad_merge" in program._annotations:
                    from ..parallel.grad_merge import (
                        _CompiledGradMergeBlock)
                    exe = _CompiledGradMergeBlock(
                        program, feed_sig, fetch_names, param_names,
                        written, scope=scope, mesh_plan=mesh_plan)
                else:
                    exe = _CompiledBlock(
                        program, feed_sig, fetch_names, param_names, written,
                        mesh_plan=mesh_plan, scope=scope,
                        report_name=report_name,
                    )
            self._cache[key] = exe
            logger.info(
                "compiled program: %d ops, %d params, %d feeds, mesh=%s",
                len(block.ops), len(param_names), len(feed_sig),
                mesh_plan.mode if mesh_plan else "single",
            )

        seed = program.random_seed or 0
        rng_key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1
        # the XLA compile happens lazily at the first execution; when the
        # persistent cache is on, attribute it as served-from-disk vs cold
        watch_cache = newly_built and bool(get_flag("FLAGS_compile_cache_dir"))
        if watch_cache:
            hits0, misses0 = compile_cache_counters()
            t0 = time.perf_counter_ns()
        _m_dispatch_slow.inc()
        _health().progress(getattr(exe, "progress_site", "executor.run"))
        t_run0 = time.perf_counter_ns()
        with _gp.timer("productive_step"), prof.RecordEvent("executor_run"):
            fetches = exe(scope, feed_arrays, rng_key)
        t_run1 = time.perf_counter_ns()
        _m_run_ms.observe((t_run1 - t_run0) / 1e6)
        if _spans.tracing_enabled():
            _spans.record("executor/step", t_run0, t_run1 - t_run0,
                          attrs={"path": "slow"})
        if watch_cache:
            hits1, misses1 = compile_cache_counters()
            if hits1 > hits0 or misses1 > misses0:
                verdict = "hit" if hits1 > hits0 else "cold"
                # counter is ALWAYS live; the trace event only exists while
                # a profiling session is active (prof.add_event guards)
                _m_compile_cache.labels(verdict).inc()
                prof.add_event(f"compile_cache/{verdict}", t0,
                               time.perf_counter_ns() - t0)
                logger.info(
                    "persistent compile cache %s for program (%d ops)",
                    verdict, len(program.global_block().ops))

        # pin the dispatch record so the next identical step skips all of
        # the normalization/keying work above
        if (use_program_cache and type(exe) is _CompiledBlock
                and get_flag("FLAGS_dispatch_fast_path")):
            key_obj = compiled if compiled is not None else program
            recs = self._dispatch_records
            if len(recs) > 256:
                recs.clear()
            recs[(id(key_obj), tuple(fetch_names))] = _DispatchRecord(
                key_obj, compiled, program, exe, feed_sig, raw_dtypes)

        if get_flag("FLAGS_check_nan_inf"):
            from ..utils.nan_inf import check_fetches

            check_fetches(fetch_names, fetches)
        if return_numpy:
            t_wait0 = time.perf_counter_ns()
            with _gp.timer("device_wait"):
                out = [np.asarray(f) for f in fetches]
            _m_device_wait_ms.observe((time.perf_counter_ns() - t_wait0) / 1e6)
            return out
        return fetches

    # ------------------------------------------------------------------
    def _check_program(self, program, feed, fetch_names) -> None:
        """FLAGS_check_program pre-compile hook: run the static verifier
        (paddle_tpu/analysis/) once per program version — errors raise
        before anything is traced, warnings go to the log. The dispatch
        fast path never reaches here (it only serves already-checked
        (program, feed, fetch) combinations)."""
        key = (id(program), program._version_token(), tuple(fetch_names))
        if key in self._checked_programs:
            return
        from .. import analysis

        result = analysis.analyze_program(
            program, feed_names=list(feed), fetch_names=fetch_names)
        for f in result.warnings:
            logger.warning("check_program: %s", f.format())
        if not result.ok:
            raise RuntimeError(
                "FLAGS_check_program: static verification failed:\n"
                + "\n".join(f.format() for f in result.errors))
        if len(self._checked_programs) > 512:
            self._checked_programs.clear()
        self._checked_programs.add(key)

    # ------------------------------------------------------------------
    # flags whose value changes the lowered computation: a rebuild whose
    # feed/fetch signature is unchanged but whose flags differ is blamed
    # on them by the recompile explainer
    _COMPILE_FLAGS = ("FLAGS_check_nan_inf", "FLAGS_check_nan_inf_level",
                      "FLAGS_fuse_optimizer", "FLAGS_roi_align_exact",
                      "FLAGS_roi_align_exact_scale")

    def _explain_rebuild(self, program, report_name, feed_sig, fetch_names,
                         mesh_plan) -> None:
        """Recompile explainer: when this program already compiled under a
        different (feed-sig, fetch, flags) signature, diff against the
        sibling history, count paddle_recompiles_total{cause=} and emit a
        rate-limited human-readable cause line."""
        from ..observability import program_report as _prep

        sig = _prep.make_sig(
            feed_sig, fetch_names,
            flags={k: get_flag(k) for k in self._COMPILE_FLAGS},
            version=program._version_token(),
            mesh=mesh_plan.signature() if mesh_plan else None)
        if len(self._compile_history) > 256:
            self._compile_history.clear()
        hist = self._compile_history.setdefault(id(program), [])
        if hist:
            cause, detail = _prep.explain_recompile(sig, hist)
            _prep.note_recompile(report_name, cause, detail)
        hist.append(sig)
        del hist[:-32]  # bound sibling history per program

    # ------------------------------------------------------------------
    def _try_fast_run(self, rec: _DispatchRecord, feed, scope, return_numpy):
        """Attempt the zero-rebuild dispatch; _FAST_MISS sends the caller
        down the full path (which re-derives and replaces the record)."""
        program = rec.program
        if (program._version_token() != rec.version
                or program.random_seed != rec.seed
                or (rec.compiled is not None
                    and rec.compiled._is_data_parallel != rec.dp_flag)):
            return _FAST_MISS
        feeds = rec.prepare(feed)
        if feeds is None:
            return _FAST_MISS
        if rec.rng_used:
            rng_key = jax.random.fold_in(rec.rng_base, self._step)
        else:
            rng_key = rec.rng_base
        self._step += 1
        self._fast_hits += 1
        _m_dispatch_fast.inc()
        # flight-recorder dispatch tick (ISSUE 19): ring-append only on
        # this path (no sidecar write unless one is attached) — the
        # <5% flight_overhead_pct A/B in tools/dispatch_bench.py holds
        # this to one global read when off, one event when on
        if _flight.flight_enabled():
            _flight.event("dispatch", path="fast", step=self._step)
        t_run0 = time.perf_counter_ns()
        prof = _prof()
        # no ledger timer here: the run() entry wrapper already brackets
        # this whole call as productive_step (fast-path overhead budget)
        if prof.is_active():
            with prof.RecordEvent("executor_run"):
                fetches = rec.exe.fast_call(scope or global_scope(),
                                            feeds, rng_key)
        else:
            fetches = rec.exe.fast_call(scope or global_scope(), feeds,
                                        rng_key)
        t_run1 = time.perf_counter_ns()
        _m_run_ms.observe((t_run1 - t_run0) / 1e6)
        # steady-state step spans: full fidelity while a profiler session
        # is live (they land on the merged-trace span plane), 1-in-64
        # sampled otherwise — a per-step record next to a ~50us jitted
        # call costs real cache locality (the <5% tracing gate in
        # tools/dispatch_bench.py)
        if _spans.tracing_enabled() and (prof.is_active()
                                         or (self._step & 63) == 0):
            _spans.record("executor/step", t_run0, t_run1 - t_run0,
                          attrs={"path": "fast"})
        if return_numpy:
            t_wait0 = time.perf_counter_ns()
            with _gp.timer("device_wait"):
                out = [np.asarray(f) for f in fetches]
            _m_device_wait_ms.observe((time.perf_counter_ns() - t_wait0) / 1e6)
            return out
        return fetches

    # ------------------------------------------------------------------
    def _run_op_level_checked(self, program, feed, fetch_names, scope,
                              return_numpy):
        """FLAGS_check_nan_inf_level=op: interpret the block EAGERLY one op
        lowering at a time, scanning every floating output on the host —
        the reference's per-op NaN/Inf localization
        (details/nan_inf_utils_detail.cc) with op attribution. Debug-only
        speed; see utils/nan_inf.py."""
        from ..utils.nan_inf import check_op_outputs

        block = program.global_block()
        env: Dict[str, Any] = {}
        for name, var in block.vars.items():
            if var.persistable and scope.has_var(name):
                env[name] = scope.find_var(name)
        for name, value in feed.items():
            env[name] = jnp.asarray(
                _normalize_feed(block.vars.get(name), value))
        seed = program.random_seed or 0
        rng_key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1
        ctx = LowerCtx(program, block, env, rng_key=rng_key)
        for op in block.ops:
            run_lowering(ctx, op)
            check_op_outputs(op, env)
        for name, var in block.vars.items():
            if var.persistable and name in env:
                scope.set_var(name, env[name])
        fetches = [env[n] for n in fetch_names]
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    # ------------------------------------------------------------------
    def run_startup(self, startup_program: Program, scope: Optional[Scope] = None):
        """Convenience alias: startup programs run through the same path."""
        return self.run(program=startup_program, feed={}, fetch_list=[], scope=scope)

    # ------------------------------------------------------------------
    # host-op segmented execution
    # ------------------------------------------------------------------
    def _segment_ops(self, ops):
        """Split the op list into maximal (is_host, [lo, hi)) runs."""
        segs = []
        lo = 0
        while lo < len(ops):
            host = ops[lo].type in _HOST_OPS
            hi = lo
            while hi < len(ops) and (ops[hi].type in _HOST_OPS) == host:
                hi += 1
            segs.append((host, lo, hi))
            lo = hi
        return segs

    def _slice_view(self, program: Program, lo: int, hi: int,
                    promote: frozenset) -> Program:
        """A derived Program running ops[lo:hi] of block 0.  Vars crossing
        the segment boundary (``promote``) get persistable=True on *copied*
        Variable objects so the compiled block reads/writes them via scope.
        Sub-blocks (control flow) are shared by reference."""
        import copy as _copy

        key = (id(program), program._version_token(), lo, hi, promote)
        view = self._view_cache.get(key)
        if view is not None:
            return view
        src_block = program.global_block()
        view = Program()
        view.random_seed = program.random_seed
        vb = view.global_block()
        for name, var in src_block.vars.items():
            v = _copy.copy(var)
            if name in promote:
                v.persistable = True
            v.block = vb
            vb.vars[name] = v
        vb.ops = list(src_block.ops[lo:hi])
        view.blocks = [vb] + program.blocks[1:]
        self._view_cache[key] = view
        if len(self._view_cache) > 256:
            self._view_cache.clear()
        return view

    def _run_with_host_ops(self, program, feed, fetch_names, scope,
                           return_numpy):
        """Execute a block containing host ops (send/recv/listen_and_serv…):
        device spans are jitted via the normal cached path; host ops run in
        Python against the scope (the reference's per-op interpreter did the
        same, executor.cc op->Run — we only drop to it at host boundaries)."""
        block = program.global_block()
        ops = block.ops
        segs = self._segment_ops(ops)

        # host ops read inputs from scope — materialize any fed values they
        # consume (device segments keep taking feeds through jit args)
        host_inputs = {n for host, lo, hi in segs if host
                       for op in ops[lo:hi] for n in op.input_arg_names}
        for n in host_inputs & feed.keys():
            scope.set_var(n, jnp.asarray(feed[n]))

        results: Dict[str, Any] = {}
        from ..profiler import RecordEvent
        for si, (host, lo, hi) in enumerate(segs):
            if host:
                for op in ops[lo:hi]:
                    with RecordEvent(f"host_op/{op.type}"):
                        _HOST_OPS[op.type](scope, op, self)
                continue
            seg_ops = ops[lo:hi]
            produced = {n for op in seg_ops for n in op.output_arg_names}
            needed_later = set(fetch_names)
            for _, l2, h2 in segs[si + 1:]:
                for op in ops[l2:h2]:
                    needed_later.update(op.input_arg_names)
            consumed_here = {n for op in seg_ops for n in op.input_arg_names}
            produced_before = {n for _, l0, h0 in segs[:si]
                               for op in ops[l0:h0]
                               for n in op.output_arg_names}
            promote = frozenset(
                (produced & needed_later)
                | (consumed_here & produced_before))
            view = self._slice_view(program, lo, hi, promote)
            seg_feed = {n: v for n, v in feed.items()
                        if n in consumed_here and n not in produced_before
                        and n not in promote}
            seg_fetch = [n for n in fetch_names if n in produced]
            vals = self.run(program=view, feed=seg_feed,
                            fetch_list=seg_fetch, scope=scope,
                            return_numpy=return_numpy)
            results.update(dict(zip(seg_fetch, vals)))

        out = []
        for n in fetch_names:
            if n in results:
                out.append(results[n])
            else:
                v = scope.find_var(n)
                if v is None:
                    raise RuntimeError(f"fetch {n!r} was never produced")
                out.append(np.asarray(v) if return_numpy else v)
        return out

    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread: int = 0, debug: bool = False,
                           fetch_list=None, fetch_info=None,
                           print_period: int = 100, monitor=None,
                           checkpoint_dir=None, checkpoint_interval=None,
                           guardrails=None):
        """Dataset trainer path — parity with fluid/executor.py:1448.

        The reference hands the Dataset to C++ trainer threads
        (Executor::RunFromDataset → HogwildWorker loops); here each parsed
        batch feeds the SAME whole-program XLA computation as ``run``. With
        ``thread > 1`` (or dataset.set_thread), file parsing and batch
        assembly run in a worker pool with a bounded prefetch queue
        (dataset.iter_batches_threaded) so host-side data work overlaps the
        asynchronously dispatched device steps — the HogwildWorker/
        MultiTrainer capability on one dispatch stream.

        ``monitor``: an ``observability.TrainMonitor``; when given, every
        step emits one structured JSONL record (step time, host-dispatch vs
        device-wait split, throughput, loss, NaN/Inf flags). Monitored runs
        sync the first fetch each step — that per-step device wait is the
        quantity being measured; leave monitor=None for the fully-async
        fast path.

        ``checkpoint_dir`` + ``checkpoint_interval``: periodic async
        crash-safe checkpointing (docs/elastic.md).  Every ``interval``
        steps the program's persistable vars plus the dataset position
        ({"epoch", "offset"}) are committed through
        ``parallel.checkpoint.ElasticCheckpointer`` (write overlapped with
        the next steps); on entry, the latest committed step is restored
        and the already-consumed batches are skipped, so a preempted job
        resumes deterministically.  A SIGTERM/SIGINT mid-train triggers a
        final synchronous checkpoint and a clean return (the launcher's
        grace-period contract).

        ``guardrails``: a ``parallel.health.GuardrailConfig`` (or ``True``
        for the defaults) arms the divergence guardrail (docs/health.md):
        each step's loss (fetch[0]) is judged, a NaN/Inf or loss-spike step
        is *skipped* — the pre-step persistable state is restored, so the
        poisoned batch never lands (the full-precision generalization of
        AMP's overflow skip; the decision depends only on the already
        all-reduced loss, so dp ranks stay in lockstep) — and after K
        consecutive bad steps the loop rolls back to the latest valid
        checkpoint with an optional LR cooldown.  Guarded runs sync the
        loss and snapshot the persistables every step — a measured,
        documented cost; leave ``guardrails=None`` for the fully-async
        fast path.  Skips/rollbacks are metered as
        ``paddle_guardrail_skipped_steps_total{reason}`` /
        ``paddle_guardrail_rollbacks_total``.
        """
        return self._run_from_dataset(program, dataset, scope, fetch_list,
                                      fetch_info, print_period, train=True,
                                      thread=thread, monitor=monitor,
                                      checkpoint_dir=checkpoint_dir,
                                      checkpoint_interval=checkpoint_interval,
                                      guardrails=guardrails)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread: int = 0, debug: bool = False,
                           fetch_list=None, fetch_info=None,
                           print_period: int = 100, monitor=None):
        """Parity with fluid/executor.py:1381 (no optimizer side effects is
        the caller's responsibility, as in the reference)."""
        return self._run_from_dataset(program, dataset, scope, fetch_list,
                                      fetch_info, print_period, train=False,
                                      thread=thread, monitor=monitor)

    def _checkpoint_state(self, program, scope) -> Dict[str, Any]:
        """Persistable vars (the trainable state) as host arrays — the
        checkpoint payload.  Host conversion here is the snapshot point."""
        out: Dict[str, Any] = {}
        for name, v in program.global_block().vars.items():
            if not v.persistable or v.is_data:
                continue
            val = scope.find_var(name)
            if val is not None:
                out[name] = np.asarray(val)
        return out

    def _restore_checkpoint_state(self, program, scope, state) -> int:
        block = program.global_block()
        n = 0
        for name, arr in state.items():
            if name in block.vars and block.vars[name].persistable:
                scope.set_var(name, jnp.asarray(arr))
                n += 1
        return n

    def _guardrail_rollback(self, program, scope, ckpt, guard, step) -> None:
        """K consecutive bad steps: restore the latest valid checkpoint
        (skip-batch already rewound this step, which is all we can do
        without a checkpoint store), cool the learning rate, and charge the
        guard's rollback budget.  The data stream is NOT rewound —
        divergence is a state problem, not a data problem (docs/health.md).
        """
        restored = None
        if ckpt is not None:
            latest = ckpt.latest_valid_step()
            if latest is not None:
                state, _man = ckpt.restore(latest)
                self._restore_checkpoint_state(program, scope, state)
                restored = latest
        cool = guard.config.lr_cooldown
        if cool != 1.0:
            # fluid optimizers keep their rate in a persistable
            # learning_rate_N global var (optimizer.py _create_lr_var)
            for name, v in program.global_block().vars.items():
                if v.persistable and name.startswith("learning_rate"):
                    val = scope.find_var(name)
                    if val is not None:
                        scope.set_var(
                            name, jnp.asarray(np.asarray(val) * cool))
        logger.warning(
            "guardrail: rollback at step %d -> %s (lr cooldown x%s)",
            step,
            f"checkpoint step {restored}" if restored is not None
            else "pre-step snapshot (no valid checkpoint)",
            cool)
        guard.rolled_back()

    def _run_from_dataset(self, program, dataset, scope, fetch_list,
                          fetch_info, print_period, train: bool,
                          thread: int = 0, monitor=None,
                          checkpoint_dir=None, checkpoint_interval=None,
                          guardrails=None):
        # goodput run window (docs/observability.md): every wall-second of
        # the dataset loop is attributed to a ledger category; the window
        # remainder becomes `other`, and the per-rank report exports to
        # PADDLE_GOODPUT_DIR for the supervisor's gang aggregation
        opened = _gp.start_window()
        try:
            return self._run_from_dataset_inner(
                program, dataset, scope, fetch_list, fetch_info,
                print_period, train, thread=thread, monitor=monitor,
                checkpoint_dir=checkpoint_dir,
                checkpoint_interval=checkpoint_interval,
                guardrails=guardrails)
        finally:
            if opened:
                _goodput.maybe_export(_gp.end_window(
                    extra={"mode": "train" if train else "infer"}))

    def _run_from_dataset_inner(self, program, dataset, scope, fetch_list,
                                fetch_info, print_period, train: bool,
                                thread: int = 0, monitor=None,
                                checkpoint_dir=None,
                                checkpoint_interval=None,
                                guardrails=None):
        if dataset is None:
            raise ValueError("dataset must be provided")
        program = program or default_main_program()
        scope = scope or global_scope()
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [
            (v.name if isinstance(v, Variable) else str(v)) for v in fetch_list
        ]
        # in-run health (docs/health.md): hang watchdog from the launcher
        # env contract, per-rank heartbeat onto the shared health dir, and
        # the optional divergence guardrail
        health = _health()
        health.maybe_install_from_env()
        hb_dir = os.environ.get(health.ENV_DIR)
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or "0")
        heartbeat = (health.RankHeartbeat(hb_dir, rank)
                     if hb_dir else None)
        # flight recorder + per-rank span sink (ISSUE 19): when the
        # launcher exports PADDLE_FLIGHT_DIR, the event ring mirrors to
        # a crash-surviving per-rank sidecar, and the span tracer writes
        # spans-train<R>-<pid>.jsonl into the same dir so
        # tools/trace_assemble.py stitches per-step training traces the
        # way it stitches serving requests
        flight_dir = os.environ.get(_flight.ENV_DIR)
        if flight_dir:
            _flight.maybe_attach_from_env()
            if _spans.tracing_enabled():
                try:
                    _spans.attach_process_sink(flight_dir, f"train{rank}")
                except OSError:
                    pass
        guard = None
        if train and guardrails is not None and guardrails is not False:
            if not fetch_list:
                raise ValueError(
                    "guardrails need a fetch_list (the loss is fetch[0])")
            guard = health.DivergenceGuard(
                guardrails if isinstance(guardrails, health.GuardrailConfig)
                else health.GuardrailConfig())
        # AMP visibility (docs/health.md): when the program carries the
        # mixed-precision loss-scaling state, mirror it into every monitor
        # row so guardrail decisions and AMP overflow-skips read off the
        # same JSONL stream
        amp_vars = None
        if monitor is not None:
            blk0 = program.global_block()
            amp_vars = {
                key: name for key, name in (
                    ("loss_scale", "loss_scaling_0"),
                    ("found_inf", "find_infinite_scale_0"),
                    ("bad_steps", "bad_steps_0"))
                if (v := blk0.vars.get(name)) is not None and v.persistable}
            if not amp_vars:
                amp_vars = None

        def _amp_fields():
            out = {}
            if amp_vars is None:
                return out
            # materializing the AMP scalars is a device sync
            with _gp.timer("device_wait"):
                return _amp_fields_inner()

        def _amp_fields_inner():
            out = {}
            v = scope.find_var(amp_vars.get("loss_scale", ""))
            if v is not None:
                out["loss_scale"] = float(np.asarray(v).ravel()[0])
            v = scope.find_var(amp_vars.get("found_inf", ""))
            if v is not None:
                out["bad_step"] = bool(np.asarray(v).ravel()[0])
            v = scope.find_var(amp_vars.get("bad_steps", ""))
            if v is not None:
                out["bad_steps"] = int(np.asarray(v).ravel()[0])
            return out
        feed_names = {v.name for v in getattr(dataset, "use_vars", [])}
        # stream-capable datasets (docs/data.md) run their own read/decode
        # worker pool — the threaded batch pipeline would bypass their
        # retry/quarantine/resume machinery
        streaming = hasattr(dataset, "stream_state") \
            and hasattr(dataset, "restore_stream_state")
        n_threads = int(thread) or int(getattr(dataset, "thread_num", 1) or 1)
        if n_threads > 1 and not streaming:
            from ..dataset import iter_batches_threaded

            batches = iter_batches_threaded(dataset, n_threads)
        else:
            batches = iter(dataset)

        def filtered():
            for batch_feed in batches:
                yield {k: v for k, v in batch_feed.items()
                       if not feed_names or k in feed_names
                       or k.endswith("__len") or k == _STREAM_STATE_KEY}

        # elastic checkpointing (docs/elastic.md): restore the latest
        # committed step into the scope, skip the consumed batches, and
        # save periodically / on preemption
        ckpt = preempt = None
        start_offset = 0
        stream_resumed = False
        if train and checkpoint_dir:
            # store bring-up (module import + committed-step scan) is
            # checkpoint machinery wall time
            with _gp.timer("checkpoint_save"):
                from ..parallel.checkpoint import ElasticCheckpointer
                from ..parallel.launch import install_preemption_handler

                scope = scope or global_scope()
                ckpt = ElasticCheckpointer(checkpoint_dir, keep_last=3)
                latest = ckpt.latest_valid_step()
            if latest is not None:
                with _gp.timer("restore"):
                    state, man = ckpt.restore(latest)
                    n_restored = self._restore_checkpoint_state(
                        program, scope, state)
                data_man = man.get("data") or {}
                start_offset = int(data_man.get("offset", 0))
                if streaming and data_man.get("stream"):
                    # a stream-capable dataset seeks to its saved per-shard
                    # offsets instead of replaying + discarding consumed
                    # batches (O(offset) parse work on every restart)
                    dataset.restore_stream_state(data_man["stream"])
                    stream_resumed = True
                logger.info(
                    "resumed %d persistables from checkpoint step %d "
                    "(%s)", n_restored, latest,
                    "stream state restored" if stream_resumed else
                    f"skipping {start_offset} consumed batches")
            preempt = install_preemption_handler()

        def _save_ckpt(step_no: int, sync: bool = False,
                       stream_state=None, span_ctx=None):
            # only the synchronous share burns main-thread wall: the host
            # snapshot + (for sync saves) the commit wait
            t_ck0 = time.perf_counter_ns()
            with _gp.timer("checkpoint_save"):
                data_state = {"epoch": 0, "offset": step_no}
                if stream_state is not None:
                    # the batch-aligned resume token of the sharded stream
                    # (docs/data.md StreamState schema)
                    data_state["stream"] = stream_state
                ckpt.save(step_no, self._checkpoint_state(program, scope),
                          data_state=data_state)
                if sync:
                    ckpt.wait()
            ck_dur = time.perf_counter_ns() - t_ck0
            _flight.event("ckpt_write", step=step_no, dur_ns=ck_dur,
                          sync=bool(sync))
            if span_ctx is not None:
                _spans.record("train/checkpoint", t_ck0, ck_dur,
                              trace=span_ctx[0], parent=span_ctx[1])

        # overlap host batch assembly + device transfer with the in-flight
        # (asynchronously dispatched) step; fetches stay on device between
        # print boundaries so the loop never blocks on the step it just
        # launched
        from ..reader import prefetch_to_device

        stream = filtered()
        if start_offset and not stream_resumed:
            import itertools

            stream = itertools.islice(stream, start_offset, None)
        step = start_offset
        last_fetch = None
        last_stream_state = None
        quarantined_fn = None
        if streaming:
            from ..dataset.streaming import quarantined_total

            quarantined_fn = quarantined_total
        batch_iter = prefetch_to_device(stream, size=2)
        while True:
            # the wait for the next staged batch is the step's input-side
            # stall; it rides every monitor row as input_wait_ms
            t_in = time.perf_counter_ns()
            try:
                feed = next(batch_iter)
            except StopIteration:
                break
            input_wait_ms = (time.perf_counter_ns() - t_in) / 1e6
            if isinstance(feed, dict):
                st = feed.pop(_STREAM_STATE_KEY, None)
                if st is not None:
                    last_stream_state = st
            # per-step flight events + a per-step root span (ISSUE 19):
            # the trace/root ids are minted up front so the dispatch /
            # data-wait / checkpoint children recorded along the way all
            # parent into the train/step root emitted at step end
            _flight.event("data_wait", dur_ns=int(input_wait_ms * 1e6),
                          step=step + 1)
            _flight.event("step_begin", step=step + 1)
            if _spans.tracing_enabled():
                step_trace, step_root = _spans.gen_id(), _spans.gen_id()
            else:
                step_trace = step_root = None
            t_disp0 = t_disp1 = None
            with _gp.timer("productive_step"):
                health.progress("train_from_dataset")
                if guard is not None:
                    # the skip-batch restore target: pre-step persistable
                    # state as host arrays (the same snapshot a checkpoint
                    # save takes — this sync + copy is guard mode's
                    # documented cost, charged to the step by the
                    # enclosing loop-body timer)
                    pre_state = self._checkpoint_state(program, scope)
                if monitor is not None:
                    if monitor.examples_per_step is None:
                        # infer the per-step example count from the batch dim
                        for v in feed.values():
                            shape = getattr(v, "shape", None)
                            if shape:
                                monitor.examples_per_step = int(shape[0])
                                break
                    # input-side context on every row (ISSUE 11 satellite):
                    # how long this step waited on the prefetch queue, and
                    # the cumulative quarantined-record count — anomaly
                    # dumps then show whether the input path was implicated
                    input_extra = {"input_wait_ms": round(input_wait_ms, 4)}
                    if quarantined_fn is not None:
                        input_extra["quarantined_records"] = \
                            int(quarantined_fn())
                    with monitor.step() as s:
                        # the dispatch IS the host-side train-step
                        # collective boundary: one monotone seq per step,
                        # agreed across ranks (identical step loops)
                        _fl_seq = _flight.collective_enter("train_step")
                        t_disp0 = time.perf_counter_ns()
                        last_fetch = self.run(program=program, feed=feed,
                                              fetch_list=fetch_list, scope=scope,
                                              return_numpy=False)
                        t_disp1 = time.perf_counter_ns()
                        _flight.collective_exit(_fl_seq, "train_step")
                        s.dispatched()
                        if fetch_list:
                            # materializing the first fetch IS the device wait;
                            # the full fetch list rides along (by reference, no
                            # sync) so an anomaly dump can summarize the
                            # offending step's values
                            extra = _amp_fields()
                            extra.update(input_extra)
                            if guard is not None:
                                with _gp.timer("device_wait"):
                                    loss_host = np.asarray(last_fetch[0])
                                verdict = guard.judge(loss_host)
                                if verdict != "ok":
                                    extra["bad_step"] = True
                            s.observe(loss=last_fetch[0], fetches=last_fetch,
                                      fetch_names=list(fetch_info), **extra)
                        else:
                            s.observe(**input_extra)
                else:
                    _fl_seq = _flight.collective_enter("train_step")
                    t_disp0 = time.perf_counter_ns()
                    last_fetch = self.run(program=program, feed=feed,
                                          fetch_list=fetch_list, scope=scope,
                                          return_numpy=False)
                    t_disp1 = time.perf_counter_ns()
                    _flight.collective_exit(_fl_seq, "train_step")
                    if guard is not None:
                        with _gp.timer("device_wait"):
                            loss_host = np.asarray(last_fetch[0])
                        verdict = guard.judge(loss_host)
                step += 1
                if heartbeat is not None:
                    heartbeat.beat(step)
                if guard is not None and verdict != "ok":
                    # skip-batch: the poisoned step's update never lands
                    with _gp.timer("rollback_replay"):
                        self._restore_checkpoint_state(program, scope, pre_state)
                        logger.warning(
                            "guardrail: step %d skipped (%s, consecutive bad "
                            "%d)", step, guard.last_reason,
                            guard.consecutive_bad)
                        if verdict == "rollback":
                            self._guardrail_rollback(program, scope, ckpt,
                                                     guard, step)
                if ckpt is not None:
                    if preempt is not None and preempt.triggered:
                        # the launcher's SIGTERM grace window: checkpoint
                        # synchronously and return cleanly
                        logger.info("preemption signal at step %d: "
                                    "checkpointing and exiting", step)
                        _save_ckpt(step, sync=True,
                                   stream_state=last_stream_state,
                                   span_ctx=(step_trace, step_root)
                                   if step_trace else None)
                        break
                    if checkpoint_interval and \
                            step % int(checkpoint_interval) == 0:
                        _save_ckpt(step, stream_state=last_stream_state,
                                   span_ctx=(step_trace, step_root)
                                   if step_trace else None)
                if fetch_list and print_period and step % print_period == 0:
                    # the only per-step host sync point (monitor excepted),
                    # and only when printing
                    t0 = time.perf_counter_ns()
                    with _gp.timer("device_wait"):
                        msg = ", ".join(
                            f"{name}={np.asarray(val).ravel()[:4]}"
                            for name, val in zip(fetch_info, last_fetch))
                    dev_ns = time.perf_counter_ns() - t0
                    _m_fetch_stall.inc(dev_ns / 1e6)
                    _flight.event("stream_fetch", step=step, dur_ns=dev_ns)
                    if step_trace is not None:
                        _spans.record("train/device", t0, dev_ns,
                                      trace=step_trace, parent=step_root)
                    logger.info("step %d: %s", step, msg)
                # step epilogue stays inside the productive_step window:
                # the flight/span sidecar flushes are framework cost of
                # the step, not unaccounted "other" in the goodput ledger
                _flight.event("step_end", step=step)
                if step_trace is not None:
                    tr = _spans.default_tracer()
                    tr.record("train/data_wait", t_in,
                              int(input_wait_ms * 1e6),
                              trace=step_trace, parent=step_root)
                    if t_disp0 is not None:
                        tr.record("train/dispatch", t_disp0,
                                  t_disp1 - t_disp0,
                                  trace=step_trace, parent=step_root)
                    tr.record("train/step", t_in,
                              time.perf_counter_ns() - t_in,
                              trace=step_trace, span_id=step_root,
                              attrs={"step": step, "rank": rank})
        if heartbeat is not None:
            heartbeat.flush()
        if ckpt is not None:
            if step > start_offset and not (preempt is not None
                                            and preempt.triggered):
                # the final save captures the dataset's CURRENT stream
                # state (epoch advanced, offsets cleared) so a relaunch
                # starts the next epoch instead of replaying the last batch
                _save_ckpt(step, sync=True,
                           stream_state=(dataset.stream_state()
                                         if streaming else None))
            ckpt.close()
        if last_fetch is not None:
            t0 = time.perf_counter_ns()
            with _gp.timer("device_wait"):
                last_fetch = [np.asarray(v) for v in last_fetch]
            _m_fetch_stall.inc((time.perf_counter_ns() - t0) / 1e6)
        return last_fetch


def _normalize_feed(var, value):
    """Cast a fed value to its declared var dtype (one rule for the jit and
    the op-level debug paths)."""
    arr = np.asarray(value)
    if var is not None and var.dtype != arr.dtype.name:
        arr = arr.astype(np.dtype(var.dtype)
                         if var.dtype != "bfloat16" else jnp.bfloat16)
    return arr


def _analyze_persistables(program: Program) -> Tuple[List[str], List[str]]:
    """Persistables read from scope vs. written back to scope by block-0 ops.

    A persistable read before any op produces it is an external input (must be
    in scope); any persistable produced by an op is written back after the run.
    Startup programs have write-only persistables (initializers) — they need no
    scope value beforehand.
    """
    block = program.global_block()
    persistable = {n for n, v in block.vars.items() if v.persistable}
    read, written = [], []
    produced: set = set()
    for op in block.ops:
        for n in op.input_arg_names:
            if n in persistable and n not in produced and n not in read:
                read.append(n)
        for n in op.output_arg_names:
            produced.add(n)
            if n in persistable and n not in written:
                written.append(n)
    return read, written
