"""Executor: compiles whole Blocks to single XLA computations.

The reference Executor (paddle/fluid/framework/executor.cc:432-494) is a per-op
interpreter: the hot loop calls op->Run per OpDesc with per-op kernel dispatch.
Here the SAME user API (``Executor.run(program, feed, fetch_list)`` — python
surface parity with fluid/executor.py:890) instead lowers the whole Block to one
jit-compiled JAX function per (program-fingerprint, feed-signature): forward,
backward and optimizer update fuse into one XLA module, parameters are donated
(buffer reuse ≙ the reference's inplace/memory passes for free).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import Place, XLAPlace, dtype_to_jax, get_flag
from .program import Program, Variable, default_main_program
from .registry import LowerCtx, run_lowering, get_op_spec, has_op

logger = logging.getLogger("paddle_tpu.executor")


class Scope:
    """Host-side name -> device array map — parity with framework/scope.h:46.

    The reference Scope is a hierarchical C++ name->Variable table; here
    variables are jax.Arrays living in HBM, and the hierarchy collapses to
    parent chaining for sub-scopes (used by control flow at lowering time).
    """

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self.parent = parent

    def var(self, name: str):
        return self._vars.setdefault(name, None)

    def set_var(self, name: str, value):
        self._vars[name] = value

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def erase(self, names: Sequence[str]):
        for n in names:
            self._vars.pop(n, None)

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


class _CompiledBlock:
    """One jit-compiled executable for (program, feed signature, fetch list)."""

    def __init__(self, program: Program, feed_sig, fetch_names, param_names,
                 written_names, mesh_axes=None, donate: bool = True):
        self.program = program
        self.feed_names = [n for n, _, _ in feed_sig]
        self.fetch_names = list(fetch_names)
        self.param_names = list(param_names)
        self.written_names = list(written_names)
        self.mesh_axes = mesh_axes or {}
        block = program.global_block()
        checkpoints = program._annotations.get("recompute_checkpoints")

        def fn(mutable_params: Dict[str, Any], const_params: Dict[str, Any],
               feeds: Dict[str, Any], rng_key):
            env: Dict[str, Any] = {}
            env.update(const_params)
            env.update(mutable_params)
            env.update(feeds)
            ctx = LowerCtx(program, block, env, rng_key=rng_key,
                           mesh_axes=self.mesh_axes)
            for op in block.ops:
                run_lowering(ctx, op)
            fetches = [env[n] for n in self.fetch_names]
            new_state = {n: env[n] for n in self.written_names if n in env}
            return fetches, new_state

        donate_args = (0,) if donate else ()
        self._jitted = jax.jit(fn, donate_argnums=donate_args)

    def __call__(self, scope: Scope, feed: Dict[str, Any], rng_key):
        mutable = {}
        const = {}
        written = set(self.written_names)
        for n in self.param_names:  # persistables read from scope
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"persistable var {n!r} is not initialized in scope — "
                    "run the startup program first"
                )
            if n in written:
                mutable[n] = v  # donated: updated in place on device
            else:
                const[n] = v
        feeds = {n: feed[n] for n in self.feed_names}
        fetches, new_state = self._jitted(mutable, const, feeds, rng_key)
        for n, v in new_state.items():
            scope.set_var(n, v)
        return fetches


class Executor:
    """User-facing executor — API parity with fluid/executor.py:890 Executor.run."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or XLAPlace(0)
        self._cache: Dict[Tuple, _CompiledBlock] = {}
        self._step = 0

    def close(self):
        self._cache.clear()

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        from .compiler import CompiledProgram

        mesh_axes = None
        if isinstance(program, CompiledProgram):
            compiled = program
            program = compiled.program
            mesh_axes = compiled._mesh_axes
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        feed = dict(feed or {})
        fetch_names = [
            v.name if isinstance(v, Variable) else str(v) for v in (fetch_list or [])
        ]

        # normalize feed values to jax arrays (device put happens inside jit)
        feed_arrays: Dict[str, Any] = {}
        feed_sig = []
        for name, value in sorted(feed.items()):
            arr = np.asarray(value)
            var = (
                program.global_block().vars.get(name)
            )
            if var is not None and var.dtype != arr.dtype.name:
                arr = arr.astype(np.dtype(var.dtype) if var.dtype != "bfloat16" else jnp.bfloat16)
            feed_arrays[name] = arr
            feed_sig.append((name, tuple(arr.shape), str(arr.dtype)))

        key = (
            id(program),
            program._version_token(),
            tuple(feed_sig),
            tuple(fetch_names),
        )
        exe = self._cache.get(key)
        if exe is None:
            block = program.global_block()
            param_names, written = _analyze_persistables(program)
            exe = _CompiledBlock(
                program, feed_sig, fetch_names, param_names, written,
                mesh_axes=mesh_axes,
            )
            self._cache[key] = exe
            logger.info(
                "compiled program: %d ops, %d params, %d feeds",
                len(block.ops), len(param_names), len(feed_sig),
            )

        seed = program.random_seed or 0
        rng_key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1
        fetches = exe(scope, feed_arrays, rng_key)

        if get_flag("FLAGS_check_nan_inf"):
            from ..utils.nan_inf import check_fetches

            check_fetches(fetch_names, fetches)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    # ------------------------------------------------------------------
    def run_startup(self, startup_program: Program, scope: Optional[Scope] = None):
        """Convenience alias: startup programs run through the same path."""
        return self.run(program=startup_program, feed={}, fetch_list=[], scope=scope)


def _analyze_persistables(program: Program) -> Tuple[List[str], List[str]]:
    """Persistables read from scope vs. written back to scope by block-0 ops.

    A persistable read before any op produces it is an external input (must be
    in scope); any persistable produced by an op is written back after the run.
    Startup programs have write-only persistables (initializers) — they need no
    scope value beforehand.
    """
    block = program.global_block()
    persistable = {n for n, v in block.vars.items() if v.persistable}
    read, written = [], []
    produced: set = set()
    for op in block.ops:
        for n in op.input_arg_names:
            if n in persistable and n not in produced and n not in read:
                read.append(n)
        for n in op.output_arg_names:
            produced.add(n)
            if n in persistable and n not in written:
                written.append(n)
    return read, written
