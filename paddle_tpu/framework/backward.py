"""IR-level autodiff: append gradient ops to a Program.

Capability parity with reference python/paddle/fluid/backward.py —
``append_backward`` (:1193) walks ops in reverse calling each op's grad maker,
sums repeated gradients (_addup_repetitive_outputs_:372), and prunes branches
that don't need grads (:454). Grad ops here are '<type>_grad' IR ops whose
default lowering is the jax.vjp of the forward lowering (registry.py) — the
program transform itself stays a first-class IR rewrite so pipeline/PS program
surgery can manipulate it, exactly like the reference.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Set

from .program import Block, Operator, Parameter, Program, Variable
from .registry import GRAD_SUFFIX, get_op_spec, has_op


def _fwd_desc(op: Operator) -> dict:
    return {
        "type": op.type,
        "inputs": {k: list(v) for k, v in op.inputs.items()},
        "outputs": {k: list(v) for k, v in op.outputs.items()},
        "attrs": {k: v for k, v in op.attrs.items() if not k.startswith("__fwd")},
    }


def _compute_requires_grad(block: Block, no_grad_set: Set[str]) -> Set[str]:
    """Forward propagation of 'requires grad' through the op list."""
    requires: Set[str] = set()
    for var in block.vars.values():
        if isinstance(var, Parameter) and var.trainable and var.name not in no_grad_set:
            requires.add(var.name)
        elif var.is_data and not var.stop_gradient and var.name not in no_grad_set:
            requires.add(var.name)
    for op in block.ops:
        if not has_op(op.type):
            continue
        spec = get_op_spec(op.type)
        if spec.grad is None:
            continue
        in_names = [n for names in op.inputs.values() for n in names]
        if any(n in requires for n in in_names):
            for n in op.output_arg_names:
                var = block.vars.get(n)
                if var is None or var.stop_gradient or n in no_grad_set:
                    continue
                requires.add(n)
    return requires


# when set (by gradients()), append_backward appends its resolved_grad closure
# so callers can resolve summed grads for arbitrary vars, not just parameters
_resolve_hook: Optional[List] = None


def append_backward(
    loss: Variable,
    parameter_list: Optional[List] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
    checkpoints: Optional[List[Variable]] = None,
) -> List:
    """Append grad ops for ``loss``; returns [(param, grad_var), ...].

    ``checkpoints`` marks recompute boundaries (parity with
    _append_backward_ops_with_checkpoints_, backward.py:629): on the TPU build
    recompute is applied at lowering time via jax.checkpoint on the segments
    between checkpoint vars (see executor.py), so here we only record them.
    """
    program: Program = loss.block.program
    block = loss.block
    no_grad = set(no_grad_set or ())
    for var in block.vars.values():
        if var.stop_gradient and not isinstance(var, Parameter):
            no_grad.add(var.name)

    requires = _compute_requires_grad(block, no_grad)
    if loss.name not in requires:
        raise ValueError(
            f"loss {loss.name!r} does not depend on any trainable parameter"
        )

    if checkpoints:
        program._annotations["recompute_checkpoints"] = [
            v.name if isinstance(v, Variable) else v for v in checkpoints
        ]

    # seed: d loss / d loss = 1
    loss_grad_name = loss.name + GRAD_SUFFIX
    block.create_var(
        name=loss_grad_name, shape=loss.shape, dtype=loss.dtype, persistable=False
    )
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad_name]},
        attrs={"shape": list(loss.shape), "dtype": loss.dtype, "value": 1.0},
    )

    # grad_map: forward var name -> list of grad var names produced so far
    grad_map: Dict[str, List[str]] = defaultdict(list)
    grad_map[loss.name].append(loss_grad_name)

    # snapshot of forward ops (exclude the seed op we just appended)
    fwd_ops = block.ops[:-1]

    def resolved_grad(name: str) -> Optional[str]:
        """Collapse accumulated grads for `name` into one var (sum if >1)."""
        lst = grad_map.get(name)
        if not lst:
            return None
        if len(lst) == 1:
            return lst[0]
        out_name = name + GRAD_SUFFIX
        if out_name in lst:
            out_name = out_name + "@SUM"
        src = block._var_recursive(name)
        block.create_var(name=out_name, shape=src.shape, dtype=src.dtype)
        block.append_op(
            type="sum", inputs={"X": list(lst)}, outputs={"Out": [out_name]}
        )
        grad_map[name] = [out_name]
        return out_name

    param_grads: Dict[str, str] = {}

    for op in reversed(fwd_ops):
        if not has_op(op.type):
            continue
        spec = get_op_spec(op.type)
        if spec.grad is None:
            continue
        # collect available out-grads
        out_grad_inputs: Dict[str, List[str]] = {}
        any_grad = False
        for slot, names in op.outputs.items():
            gs = []
            for n in names:
                g = resolved_grad(n)
                gs.append(g)
                if g is not None:
                    any_grad = True
            if any(g is not None for g in gs):
                # missing grads in a slot are represented by zero-filled vars
                filled = []
                for n, g in zip(names, gs):
                    if g is None:
                        src = block._var_recursive(n)
                        zname = n + GRAD_SUFFIX + "@ZERO"
                        if not block.has_var(zname):
                            block.create_var(name=zname, shape=src.shape, dtype=src.dtype)
                            block.append_op(
                                type="fill_zeros_like",
                                inputs={"X": [n]},
                                outputs={"Out": [zname]},
                            )
                        g = zname
                    filled.append(g)
                out_grad_inputs[slot + GRAD_SUFFIX] = filled
        if not any_grad:
            continue

        # which inputs need grads?
        if spec.diff_inputs is not None:
            cand_slots = [s for s in spec.diff_inputs if s in op.inputs]
        else:
            cand_slots = list(op.inputs.keys())
        grad_outputs: Dict[str, List[str]] = {}
        for slot in cand_slots:
            outs = []
            needed = False
            for n in op.inputs[slot]:
                if n in requires and n not in no_grad:
                    gname = _fresh_grad_name(block, n, grad_map)
                    src = block._var_recursive(n)
                    block.create_var(name=gname, shape=src.shape, dtype=src.dtype)
                    outs.append(gname)
                    needed = True
                else:
                    outs.append(None)
            if needed:
                grad_outputs[slot + GRAD_SUFFIX] = outs
        if not grad_outputs:
            continue

        if callable(spec.grad):
            # custom grad maker appends its own ops
            spec.grad(op, block, out_grad_inputs, grad_outputs)
        else:
            g_inputs: Dict[str, List[str]] = {}
            for slot, names in op.inputs.items():
                g_inputs[slot] = list(names)
            for slot, names in op.outputs.items():
                if slot not in g_inputs:
                    g_inputs[slot] = list(names)
            g_inputs.update(out_grad_inputs)
            # keep positional alignment with the forward input list: unneeded
            # grads become the @EMPTY@ placeholder (skipped at bind time), so
            # the vjp lowering's per-slot cotangent list stays index-aligned.
            g_outputs = {
                slot: [n if n is not None else "@EMPTY@" for n in outs]
                for slot, outs in grad_outputs.items()
            }
            attrs = dict(op.attrs)
            attrs["__fwd__"] = _fwd_desc(op)
            block.append_op(
                type=op.type + "_grad",
                inputs=g_inputs,
                outputs=g_outputs,
                attrs=attrs,
            )

        # record produced grads
        for slot, outs in grad_outputs.items():
            src_slot = slot[: -len(GRAD_SUFFIX)]
            for n, g in zip(op.inputs[src_slot], outs):
                if g is not None:
                    grad_map[n].append(g)

    # final (param, grad) pairing
    if parameter_list is not None:
        params = [
            p if isinstance(p, Parameter) else block._var_recursive(p)
            for p in parameter_list
        ]
    else:
        params = [p for p in block.all_parameters() if p.trainable]

    result = []
    for p in params:
        if p.name in no_grad:
            continue
        g = resolved_grad(p.name)
        if g is None:
            continue
        gvar = block._var_recursive(g)
        result.append((p, gvar))
    if _resolve_hook is not None:
        _resolve_hook.append(resolved_grad)
    return result


def _fresh_grad_name(block: Block, name: str, grad_map) -> str:
    base = name + GRAD_SUFFIX
    if not grad_map[name] and not block.has_var(base):
        return base
    i = len(grad_map[name])
    while block.has_var(f"{base}@RENAME@{i}"):
        i += 1
    return f"{base}@RENAME@{i}"


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.fluid.gradients parity: grads of targets wrt inputs."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    # Implemented via append_backward on a sum-of-targets scalar.
    block = targets[0].block
    if len(targets) == 1 and targets[0].shape in ((), (1,)):
        loss = targets[0]
    else:
        from ..layers import tensor as tl

        summed = [tl.reduce_sum_var(t) for t in targets]
        loss = summed[0]
        for s in summed[1:]:
            loss = loss + s
    global _resolve_hook
    hook: List = []
    _resolve_hook = hook
    try:
        pg = append_backward(loss, parameter_list=None, no_grad_set=no_grad_set)
    finally:
        _resolve_hook = None
    resolved_grad = hook[0] if hook else None
    grad_by_name = {p.name: g for p, g in pg}
    out = []
    for iv in inputs:
        g = grad_by_name.get(iv.name)
        if g is None and resolved_grad is not None:
            gname = resolved_grad(iv.name)
            if gname is not None:
                g = iv.block._var_recursive(gname)
        out.append(g)
    return out
