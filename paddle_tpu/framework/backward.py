"""IR-level autodiff: append gradient ops to a Program.

Capability parity with reference python/paddle/fluid/backward.py —
``append_backward`` (:1193) walks ops in reverse calling each op's grad maker,
sums repeated gradients (_addup_repetitive_outputs_:372), and prunes branches
that don't need grads (:454). Grad ops here are '<type>_grad' IR ops whose
default lowering is the jax.vjp of the forward lowering (registry.py) — the
program transform itself stays a first-class IR rewrite so pipeline/PS program
surgery can manipulate it, exactly like the reference.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Set

from .program import Block, Operator, Parameter, Program, Variable
from .registry import GRAD_SUFFIX, get_op_spec, has_op


def _fwd_desc(op: Operator, rename: Optional[Dict[str, str]] = None) -> dict:
    r = rename or {}
    desc = {
        "type": op.type,
        "inputs": {k: [r.get(n, n) for n in v] for k, v in op.inputs.items()},
        "outputs": {k: [r.get(n, n) for n in v] for k, v in op.outputs.items()},
        "attrs": {k: v for k, v in op.attrs.items() if not k.startswith("__fwd")},
    }
    if r:
        # pin the original output names for rng replay so recomputed random
        # ops (dropout masks) reproduce the forward's randomness exactly
        desc["attrs"]["__rng_names__"] = sorted(
            n for ns in op.outputs.values() for n in ns)
    return desc


def _compute_requires_grad(block: Block, no_grad_set: Set[str]) -> Set[str]:
    """Forward propagation of 'requires grad' through the op list."""
    requires: Set[str] = set()
    for var in block.vars.values():
        if isinstance(var, Parameter) and var.trainable and var.name not in no_grad_set:
            requires.add(var.name)
        elif var.is_data and not var.stop_gradient and var.name not in no_grad_set:
            requires.add(var.name)
    for op in block.ops:
        if not has_op(op.type):
            continue
        spec = get_op_spec(op.type)
        if spec.grad is None:
            continue
        in_names = [n for names in op.inputs.values() for n in names]
        if any(n in requires for n in in_names):
            for n in op.output_arg_names:
                var = block.vars.get(n)
                if var is None or var.stop_gradient or n in no_grad_set:
                    continue
                requires.add(n)
    return requires


# when set (by gradients()), append_backward appends its resolved_grad closure
# so callers can resolve summed grads for arbitrary vars, not just parameters
_resolve_hook: Optional[List] = None


class _RecomputePlan:
    """Segment bookkeeping for checkpoint recompute — the IR-transform parity
    of _append_backward_ops_with_checkpoints_ (reference backward.py:629).

    Forward ops are split into segments ending at each checkpoint-producing
    op; when the reverse walk reaches a segment's first grad op, the segment's
    forward ops are re-emitted with renamed outputs, fed through a
    `recompute_barrier` (lax.optimization_barrier) on the segment's external
    inputs so XLA CSE cannot merge the recomputation with the original
    forward.  Grad ops of the segment then replay against the recomputed
    values; the original intermediates die at the end of the forward, which is
    the whole memory saving.  The tail after the last checkpoint is not
    recomputed (same as the reference and jax.checkpoint).
    """

    def __init__(self, block: Block, fwd_ops: List[Operator],
                 ckpt_names: List[str]):
        self.block = block
        self.fwd_ops = fwd_ops
        self.ckpt_names = set(ckpt_names)
        prod_idx: Dict[str, int] = {}
        for i, op in enumerate(fwd_ops):
            for n in op.output_arg_names:
                if n in self.ckpt_names:
                    prod_idx[n] = i
        cuts = sorted(set(prod_idx.values()))
        self.segments: List = []
        lo = 0
        for c in cuts:
            if c >= lo:
                self.segments.append((lo, c))
                lo = c + 1
        self.seg_of: Dict[int, int] = {}
        for s, (a, b) in enumerate(self.segments):
            for i in range(a, b + 1):
                self.seg_of[i] = s
        self.rename: List[Optional[Dict[str, str]]] = [None] * len(self.segments)

    def rename_for(self, op_index: int) -> Optional[Dict[str, str]]:
        """Materialize (once) the segment containing op_index; return its
        name map (original -> recomputed/barriered) or None for the tail."""
        s = self.seg_of.get(op_index)
        if s is None:
            return None
        if self.rename[s] is not None:
            return self.rename[s]
        a, b = self.segments[s]
        seg_ops = self.fwd_ops[a:b + 1]
        produced = {n for op in seg_ops for n in op.output_arg_names}
        rename = {n: f"{n}@RC{s}" for n in produced
                  if n not in self.ckpt_names}
        ext: List[str] = []
        for op in seg_ops:
            for n in op.input_arg_names:
                if n not in produced and n not in ext:
                    ext.append(n)
        bar = {n: f"{n}@BAR{s}" for n in ext}
        block = self.block
        for n, bn in bar.items():
            src = block._var_recursive(n)
            block.create_var(name=bn, shape=src.shape, dtype=src.dtype)
        if bar:
            block.append_op(type="recompute_barrier",
                            inputs={"X": list(bar)},
                            outputs={"Out": list(bar.values())})
        full = {**bar, **rename}
        for op in seg_ops:
            if not has_op(op.type):
                continue
            if not any(n in rename for n in op.output_arg_names):
                continue  # all outputs are stored checkpoints — nothing to redo
            # a multi-output op may produce both an intermediate and a stored
            # checkpoint; route the checkpoint output to a dummy var so the
            # original binding is not clobbered
            out_name = {n: rename.get(n, f"{n}@RCdup{s}")
                        for n in op.output_arg_names}
            for n, rn in out_name.items():
                src = block._var_recursive(n)
                block.create_var(name=rn, shape=src.shape, dtype=src.dtype)
            new_attrs = dict(op.attrs)
            new_attrs["__rng_names__"] = sorted(op.output_arg_names)
            block.append_op(
                type=op.type,
                inputs={k: [full.get(n, n) for n in v]
                        for k, v in op.inputs.items()},
                outputs={k: [out_name[n] for n in v]
                         for k, v in op.outputs.items()},
                attrs=new_attrs,
            )
        self.rename[s] = full
        return full


def append_backward(
    loss: Variable,
    parameter_list: Optional[List] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
    checkpoints: Optional[List[Variable]] = None,
) -> List:
    """Append grad ops for ``loss``; returns [(param, grad_var), ...].

    ``checkpoints`` marks recompute boundaries (parity with
    _append_backward_ops_with_checkpoints_, backward.py:629): forward segments
    ending at each checkpoint are re-emitted into the backward region behind a
    `recompute_barrier` op (lax.optimization_barrier), and the segment's grad
    ops replay against the recomputed values — see _RecomputePlan.
    """
    program: Program = loss.block.program
    block = loss.block
    no_grad = set(no_grad_set or ())
    for var in block.vars.values():
        if var.stop_gradient and not isinstance(var, Parameter):
            no_grad.add(var.name)

    requires = _compute_requires_grad(block, no_grad)
    if loss.name not in requires:
        raise ValueError(
            f"loss {loss.name!r} does not depend on any trainable parameter"
        )

    ckpt_names = [v.name if isinstance(v, Variable) else v
                  for v in (checkpoints or ())]
    if ckpt_names:
        # introspection-only metadata (tooling/tests); the actual recompute
        # transform is _RecomputePlan below, not an executor-side consumer
        program._annotations["recompute_checkpoints"] = list(ckpt_names)

    # everything appended from here is the backward slice
    # (clone(for_test=True) strips it by this role tag)
    with program.op_role_guard(Program.OP_ROLE_BACKWARD):
        return _append_backward_tagged(loss, block, program, requires,
                                       no_grad, ckpt_names, parameter_list)


def _append_backward_tagged(loss, block, program, requires, no_grad,
                            ckpt_names, parameter_list):
    # seed: d loss / d loss = 1
    loss_grad_name = loss.name + GRAD_SUFFIX
    block.create_var(
        name=loss_grad_name, shape=loss.shape, dtype=loss.dtype, persistable=False
    )
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad_name]},
        attrs={"shape": list(loss.shape), "dtype": loss.dtype, "value": 1.0},
    )

    # grad_map: forward var name -> list of grad var names produced so far
    grad_map: Dict[str, List[str]] = defaultdict(list)
    grad_map[loss.name].append(loss_grad_name)

    # snapshot of forward ops (exclude the seed op we just appended)
    fwd_ops = block.ops[:-1]
    recompute = _RecomputePlan(block, fwd_ops, ckpt_names) if ckpt_names else None

    def resolved_grad(name: str) -> Optional[str]:
        """Collapse accumulated grads for `name` into one var (sum if >1)."""
        lst = grad_map.get(name)
        if not lst:
            return None
        if len(lst) == 1:
            return lst[0]
        out_name = name + GRAD_SUFFIX
        if out_name in lst:
            out_name = out_name + "@SUM"
        src = block._var_recursive(name)
        block.create_var(name=out_name, shape=src.shape, dtype=src.dtype)
        block.append_op(
            type="sum", inputs={"X": list(lst)}, outputs={"Out": [out_name]}
        )
        grad_map[name] = [out_name]
        return out_name

    param_grads: Dict[str, str] = {}

    for op_index in range(len(fwd_ops) - 1, -1, -1):
        op = fwd_ops[op_index]
        if not has_op(op.type):
            continue
        spec = get_op_spec(op.type)
        if spec.grad is None:
            continue
        # collect available out-grads
        out_grad_inputs: Dict[str, List[str]] = {}
        any_grad = False
        for slot, names in op.outputs.items():
            gs = []
            for n in names:
                g = resolved_grad(n)
                gs.append(g)
                if g is not None:
                    any_grad = True
            if any(g is not None for g in gs):
                # missing grads in a slot are represented by zero-filled vars
                filled = []
                for n, g in zip(names, gs):
                    if g is None:
                        src = block._var_recursive(n)
                        zname = n + GRAD_SUFFIX + "@ZERO"
                        if not block.has_var(zname):
                            block.create_var(name=zname, shape=src.shape, dtype=src.dtype)
                            block.append_op(
                                type="fill_zeros_like",
                                inputs={"X": [n]},
                                outputs={"Out": [zname]},
                            )
                        g = zname
                    filled.append(g)
                out_grad_inputs[slot + GRAD_SUFFIX] = filled
        if not any_grad:
            continue

        # which inputs need grads?
        if spec.diff_inputs is not None:
            cand_slots = [s for s in spec.diff_inputs if s in op.inputs]
        else:
            cand_slots = list(op.inputs.keys())
        grad_outputs: Dict[str, List[str]] = {}
        for slot in cand_slots:
            outs = []
            needed = False
            for n in op.inputs[slot]:
                if n in requires and n not in no_grad:
                    gname = _fresh_grad_name(block, n, grad_map)
                    src = block._var_recursive(n)
                    block.create_var(name=gname, shape=src.shape, dtype=src.dtype)
                    outs.append(gname)
                    needed = True
                else:
                    outs.append(None)
            if needed:
                grad_outputs[slot + GRAD_SUFFIX] = outs
        if not grad_outputs:
            continue

        # recompute: materialize the segment's re-emitted forward (once) and
        # rewrite the grad op's forward-value references to the recomputed
        # names; grad var names stay original so cross-segment grad flow and
        # the final (param, grad) pairing are untouched
        rmap = recompute.rename_for(op_index) if recompute else None

        if callable(spec.grad):
            # custom grad maker appends its own ops
            grad_src_op = op
            if rmap:
                import copy as _copy
                grad_src_op = _copy.copy(op)
                grad_src_op.inputs = {
                    k: [rmap.get(n, n) for n in v] for k, v in op.inputs.items()}
                grad_src_op.outputs = {
                    k: [rmap.get(n, n) for n in v] for k, v in op.outputs.items()}
            spec.grad(grad_src_op, block, out_grad_inputs, grad_outputs)
        else:
            g_inputs: Dict[str, List[str]] = {}
            for slot, names in op.inputs.items():
                g_inputs[slot] = [rmap.get(n, n) for n in names] if rmap else list(names)
            for slot, names in op.outputs.items():
                if slot not in g_inputs:
                    g_inputs[slot] = [rmap.get(n, n) for n in names] if rmap else list(names)
            g_inputs.update(out_grad_inputs)
            # keep positional alignment with the forward input list: unneeded
            # grads become the @EMPTY@ placeholder (skipped at bind time), so
            # the vjp lowering's per-slot cotangent list stays index-aligned.
            g_outputs = {
                slot: [n if n is not None else "@EMPTY@" for n in outs]
                for slot, outs in grad_outputs.items()
            }
            attrs = dict(op.attrs)
            attrs["__fwd__"] = _fwd_desc(op, rmap)
            block.append_op(
                type=op.type + "_grad",
                inputs=g_inputs,
                outputs=g_outputs,
                attrs=attrs,
            )

        # record produced grads
        for slot, outs in grad_outputs.items():
            src_slot = slot[: -len(GRAD_SUFFIX)]
            for n, g in zip(op.inputs[src_slot], outs):
                if g is not None:
                    grad_map[n].append(g)

    # final (param, grad) pairing
    if parameter_list is not None:
        params = [
            p if isinstance(p, Parameter) else block._var_recursive(p)
            for p in parameter_list
        ]
    else:
        params = [p for p in block.all_parameters() if p.trainable]

    result = []
    for p in params:
        if p.name in no_grad:
            continue
        g = resolved_grad(p.name)
        if g is None:
            continue
        gvar = block._var_recursive(g)
        result.append((p, gvar))
    if _resolve_hook is not None:
        _resolve_hook.append(resolved_grad)
    return result


def _fresh_grad_name(block: Block, name: str, grad_map) -> str:
    base = name + GRAD_SUFFIX
    if not grad_map[name] and not block.has_var(base):
        return base
    i = len(grad_map[name])
    while block.has_var(f"{base}@RENAME@{i}"):
        i += 1
    return f"{base}@RENAME@{i}"


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.fluid.gradients parity: grads of targets wrt inputs."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    # Implemented via append_backward on a sum-of-targets scalar.
    block = targets[0].block
    if len(targets) == 1 and targets[0].shape in ((), (1,)):
        loss = targets[0]
    else:
        from ..layers import tensor as tl

        summed = [tl.reduce_sum_var(t) for t in targets]
        loss = summed[0]
        for s in summed[1:]:
            loss = loss + s
    global _resolve_hook
    hook: List = []
    _resolve_hook = hook
    try:
        pg = append_backward(loss, parameter_list=None, no_grad_set=no_grad_set)
    finally:
        _resolve_hook = None
    resolved_grad = hook[0] if hook else None
    grad_by_name = {p.name: g for p, g in pg}
    out = []
    for iv in inputs:
        g = grad_by_name.get(iv.name)
        if g is None and resolved_grad is not None:
            gname = resolved_grad(iv.name)
            if gname is not None:
                g = iv.block._var_recursive(gname)
        out.append(g)
    return out
