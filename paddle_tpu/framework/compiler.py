"""CompiledProgram / BuildStrategy — parity with python/paddle/fluid/compiler.py
(CompiledProgram:87, with_data_parallel:160) and framework/details/
build_strategy.h:58-141.

The reference's with_data_parallel builds a multi-GPU SSA graph executed by
ParallelExecutor with NCCL allreduce op-handles. Here the SAME API instead
annotates the program for mesh execution: the Executor shards the batch over a
data-parallel jax.sharding.Mesh axis and XLA inserts the gradient allreduce —
ParallelExecutor, op handles and NCCL rings have no equivalent code because
GSPMD subsumes them (SURVEY.md §2.3).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax


@dataclasses.dataclass
class BuildStrategy:
    """Knob parity with details/build_strategy.h. Most knobs are XLA-owned;
    they are accepted and recorded so reference scripts run unmodified."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    reduce_strategy: int = 0
    gradient_scale_strategy: int = 0
    debug_graphviz_path: str = ""
    enable_sequential_execution: bool = False
    fuse_elewise_add_act_ops: bool = False  # XLA fuses anyway
    fuse_bn_act_ops: bool = False
    fuse_relu_depthwise_conv: bool = False
    fuse_broadcast_ops: bool = False
    fuse_all_optimizer_ops: bool = False
    fuse_all_reduce_ops: bool = False
    enable_inplace: bool = True  # donation ≙ inplace
    memory_optimize: bool = True
    sync_batch_norm: bool = False
    num_trainers: int = 1
    trainer_id: int = 0
    nccl_comm_num: int = 1
    use_hierarchical_allreduce: bool = False
    hierarchical_allreduce_inter_nranks: int = 0


@dataclasses.dataclass
class ExecutionStrategy:
    num_threads: int = 0
    num_iteration_per_drop_scope: int = 100
    num_iteration_per_run: int = 1
    use_thread_barrier: bool = False


def rewrite_sync_batch_norm(program):
    """reference compiler.py:367: sync_batch_norm rewrites every BN op in the
    multi-device graph to the cross-rank variant (both directions: the grad
    op's vjp replay must re-trace the sync forward so the collective
    transposes appear in the backward). Note the gspmd engine needs no
    rewrite — a batch-sharded jnp.mean is already a global reduction — this
    is for shard_map (per-rank) programs, where plain BN sees local stats."""
    for op in program.global_block().ops:
        if op.type == "batch_norm":
            op.type = "sync_batch_norm"
        elif op.type == "batch_norm_grad":
            op.type = "sync_batch_norm_grad"
            fwd = op.attrs.get("__fwd__")
            if fwd:
                fwd["type"] = "sync_batch_norm"


class CompiledProgram:
    """Wraps a Program with execution annotations. `with_data_parallel`
    switches the Executor into mesh (pjit) mode over all local devices."""

    def __init__(self, program_or_graph, build_strategy: Optional[BuildStrategy] = None):
        self.program = program_or_graph
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = ExecutionStrategy()
        self._is_data_parallel = False
        self._loss_name = None
        self._share_vars_from = None
        self._places = None
        # ring_id -> mesh axis name (collective ops lower over these)
        self._mesh_axes = {}
        self._data_parallel_axis = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self.build_strategy = build_strategy
        if exec_strategy is not None:
            self.exec_strategy = exec_strategy
        self._share_vars_from = share_vars_from
        self._places = places
        self._data_parallel_axis = "dp"
        self._mesh_axes = {0: "dp"}
        self.program._annotations["data_parallel"] = True
        if self.build_strategy.sync_batch_norm and \
                hasattr(self.program, "global_block"):
            rewrite_sync_batch_norm(self.program)
        return self

    @property
    def num_devices(self):
        if self._places is not None:
            return len(self._places)
        return jax.local_device_count()
