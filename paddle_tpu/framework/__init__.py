from . import core, unique_name  # noqa: F401
from .program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
)
