from . import core, unique_name  # noqa: F401
from .program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
)

# paddle-2.0-preview `paddle.framework` surface (reference
# python/paddle/framework/__init__.py) — aliases of the fluid machinery plus
# the random-seed control.
from . import random  # noqa: F401
from .random import manual_seed  # noqa: F401
from .backward import append_backward, gradients  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .core import CPUPlace, TPUPlace, XLAPlace  # noqa: F401
# reference paddle.framework re-exports the CUDA places; on TPU both alias
# the accelerator place (top-level __init__ establishes the same aliases)
from .core import XLAPlace as CUDAPlace  # noqa: F401
from .core import XLAPlace as CUDAPinnedPlace  # noqa: F401
from .executor import Executor, global_scope, scope_guard  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401


def __getattr__(name):
    # layer-built entries of the 2.0 surface resolve lazily: the layers
    # package imports framework, so a top-level import here would cycle
    if name in ("Print", "py_func", "create_global_var", "create_parameter"):
        from .. import layers
        return getattr(layers, name)
    if name == "ParallelExecutor":
        from ..parallel_executor import ParallelExecutor
        return ParallelExecutor
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
