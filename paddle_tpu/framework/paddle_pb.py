"""Reference-compatible binary serialization of the Program IR.

The reference persists programs as a proto2 `ProgramDesc` message
(/root/reference/paddle/fluid/framework/framework.proto:42-216) and tensors as
a versioned binary stream (/root/reference/paddle/fluid/framework/
tensor_util.cc `TensorToStream`, lod_tensor.cc:220 `SerializeToStream`,
save_load_util.cc).  This module implements both formats directly on the
proto2 *wire encoding* — schema tables + a ~100-line varint codec — so the
framework can exchange `__model__` / params artifacts with the reference
without a protobuf build step or a copied .proto file.

Wire compatibility is cross-checked in tests against an independently
constructed `google.protobuf` dynamic descriptor of the same schema.

Encoded/decoded values use the in-repo desc-dict shape produced by
`Program._desc_dict()` (framework/program.py) so `serialization.py`'s
`program_from_desc` can rebuild a Program from either JSON or protobuf.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .core import VarType

# ---------------------------------------------------------------------------
# proto2 wire primitives
# ---------------------------------------------------------------------------

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_BYTES = 2
_WIRE_32BIT = 5


def _uvarint(value: int) -> bytes:
    """Encode a non-negative int as a base-128 varint."""
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _svarint(value: int) -> bytes:
    """Encode a (possibly negative) int the way proto2 int32/int64 do:
    two's-complement in 64 bits, then varint."""
    return _uvarint(value & 0xFFFFFFFFFFFFFFFF)


def _tag(field: int, wire: int) -> bytes:
    return _uvarint((field << 3) | wire)


def _field_varint(field: int, value: int) -> bytes:
    return _tag(field, _WIRE_VARINT) + _svarint(int(value))


def _field_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, _WIRE_BYTES) + _uvarint(len(payload)) + payload


def _field_str(field: int, s: str) -> bytes:
    return _field_bytes(field, s.encode("utf-8"))


def _field_f32(field: int, value: float) -> bytes:
    return _tag(field, _WIRE_32BIT) + struct.pack("<f", float(value))


class _Reader:
    """Cursor over a proto2 message body yielding (field, wire, value)."""

    def __init__(self, data: bytes, start: int = 0, end: Optional[int] = None):
        self.data = data
        self.pos = start
        self.end = len(data) if end is None else end

    def _read_uvarint(self) -> int:
        shift = 0
        result = 0
        while True:
            if self.pos >= self.end:
                raise ValueError("truncated varint in ProgramDesc stream")
            byte = self.data[self.pos]
            self.pos += 1
            result |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return result
            shift += 7

    def fields(self):
        while self.pos < self.end:
            key = self._read_uvarint()
            field, wire = key >> 3, key & 0x7
            if wire == _WIRE_VARINT:
                yield field, wire, self._read_uvarint()
            elif wire == _WIRE_BYTES:
                size = self._read_uvarint()
                start = self.pos
                self.pos += size
                if self.pos > self.end:
                    raise ValueError("truncated length-delimited field")
                yield field, wire, self.data[start:self.pos]
            elif wire == _WIRE_32BIT:
                start = self.pos
                self.pos += 4
                yield field, wire, self.data[start:self.pos]
            elif wire == _WIRE_64BIT:
                start = self.pos
                self.pos += 8
                yield field, wire, self.data[start:self.pos]
            else:
                raise ValueError(f"unsupported wire type {wire}")


def _to_i64(u: int) -> int:
    """Reinterpret an unsigned varint value as a signed 64-bit int."""
    return u - (1 << 64) if u >= (1 << 63) else u


def _varints_in(value, packed_ok=True) -> List[int]:
    """A repeated varint field arrives either as one unpacked value or (from
    packed writers) as a length-delimited blob of varints; accept both."""
    if isinstance(value, int):
        return [value]
    out = []
    r = _Reader(value)
    while r.pos < r.end:
        out.append(r._read_uvarint())
    return out


def _f32s_in(value) -> List[float]:
    if isinstance(value, bytes) and len(value) == 4:
        return [struct.unpack("<f", value)[0]]
    # packed
    return [struct.unpack_from("<f", value, i)[0] for i in range(0, len(value), 4)]


# ---------------------------------------------------------------------------
# AttrType enumeration (framework.proto:26-38)
# ---------------------------------------------------------------------------

ATTR_INT = 0
ATTR_FLOAT = 1
ATTR_STRING = 2
ATTR_INTS = 3
ATTR_FLOATS = 4
ATTR_STRINGS = 5
ATTR_BOOLEAN = 6
ATTR_BOOLEANS = 7
ATTR_BLOCK = 8
ATTR_LONG = 9
ATTR_BLOCKS = 10
ATTR_LONGS = 11

# Attr names whose int payload is a Block index in this IR (control flow).
_BLOCK_ATTR_NAMES = {"sub_block", "forward_block", "backward_block"}
_BLOCKS_ATTR_NAMES = {"blocks", "sub_blocks"}

_INT32_MIN, _INT32_MAX = -(1 << 31), (1 << 31) - 1


def _classify_attr(name: str, value) -> Tuple[int, object]:
    """Infer the proto AttrType for a plain-python attr value."""
    if isinstance(value, bool):
        return ATTR_BOOLEAN, value
    if isinstance(value, (int, np.integer)):
        if name in _BLOCK_ATTR_NAMES:
            return ATTR_BLOCK, int(value)
        if _INT32_MIN <= value <= _INT32_MAX:
            return ATTR_INT, int(value)
        return ATTR_LONG, int(value)
    if isinstance(value, (float, np.floating)):
        return ATTR_FLOAT, float(value)
    if isinstance(value, str):
        return ATTR_STRING, value
    if isinstance(value, (list, tuple, np.ndarray)):
        items = list(value)
        if name in _BLOCKS_ATTR_NAMES:
            return ATTR_BLOCKS, [int(v) for v in items]
        if not items:
            return ATTR_INTS, []
        if all(isinstance(v, bool) for v in items):
            return ATTR_BOOLEANS, items
        if all(isinstance(v, (int, np.integer)) for v in items):
            if all(_INT32_MIN <= v <= _INT32_MAX for v in items):
                return ATTR_INTS, [int(v) for v in items]
            return ATTR_LONGS, [int(v) for v in items]
        if all(isinstance(v, str) for v in items):
            return ATTR_STRINGS, items
        return ATTR_FLOATS, [float(v) for v in items]
    raise TypeError(f"attr {name!r}: cannot serialize value of type {type(value)}")


def _attr_to_pb(name: str, value) -> Optional[bytes]:
    if value is None:
        return None  # proto2 has no null attr; reference never stores one
    atype, v = _classify_attr(name, value)
    body = _field_str(1, name) + _field_varint(2, atype)
    if atype == ATTR_INT:
        body += _field_varint(3, v)
    elif atype == ATTR_FLOAT:
        body += _field_f32(4, v)
    elif atype == ATTR_STRING:
        body += _field_str(5, v)
    elif atype == ATTR_INTS:
        body += b"".join(_field_varint(6, x) for x in v)
    elif atype == ATTR_FLOATS:
        body += b"".join(_field_f32(7, x) for x in v)
    elif atype == ATTR_STRINGS:
        body += b"".join(_field_str(8, x) for x in v)
    elif atype == ATTR_BOOLEAN:
        body += _field_varint(10, 1 if v else 0)
    elif atype == ATTR_BOOLEANS:
        body += b"".join(_field_varint(11, 1 if x else 0) for x in v)
    elif atype == ATTR_BLOCK:
        body += _field_varint(12, v)
    elif atype == ATTR_LONG:
        body += _field_varint(13, v)
    elif atype == ATTR_BLOCKS:
        body += b"".join(_field_varint(14, x) for x in v)
    elif atype == ATTR_LONGS:
        body += b"".join(_field_varint(15, x) for x in v)
    return body


def _attr_from_pb(data: bytes):
    name = None
    atype = None
    scalar = None
    rep: List = []
    for field, wire, value in _Reader(data).fields():
        if field == 1:
            name = value.decode("utf-8")
        elif field == 2:
            atype = value
        elif field == 3:  # i
            scalar = _to_i64(value)
        elif field == 4:  # f
            scalar = _f32s_in(value)[0]
        elif field == 5:  # s
            scalar = value.decode("utf-8")
        elif field == 6:  # ints
            rep += [_to_i64(v) for v in _varints_in(value)]
        elif field == 7:  # floats
            rep += _f32s_in(value)
        elif field == 8:  # strings
            rep.append(value.decode("utf-8"))
        elif field == 10:  # b
            scalar = bool(value)
        elif field == 11:  # bools
            rep += [bool(v) for v in _varints_in(value)]
        elif field == 12:  # block_idx
            scalar = _to_i64(value)
        elif field == 13:  # l
            scalar = _to_i64(value)
        elif field == 14:  # blocks_idx
            rep += [_to_i64(v) for v in _varints_in(value)]
        elif field == 15:  # longs
            rep += [_to_i64(v) for v in _varints_in(value)]
    if atype in (ATTR_INTS, ATTR_FLOATS, ATTR_STRINGS, ATTR_BOOLEANS,
                 ATTR_BLOCKS, ATTR_LONGS):
        return name, rep
    return name, scalar


# ---------------------------------------------------------------------------
# dtype <-> VarType.Type
# ---------------------------------------------------------------------------

_DTYPE_TO_PROTO = {
    "bool": int(VarType.BOOL),
    "int16": int(VarType.INT16),
    "int32": int(VarType.INT32),
    "int64": int(VarType.INT64),
    "float16": int(VarType.FP16),
    "float32": int(VarType.FP32),
    "float64": int(VarType.FP64),
    "uint8": int(VarType.UINT8),
    "int8": int(VarType.INT8),
    # The reference proto has no bfloat16; persist as FP32 (cast on save).
    "bfloat16": int(VarType.FP32),
}
_PROTO_TO_DTYPE = {
    int(VarType.BOOL): "bool",
    int(VarType.INT16): "int16",
    int(VarType.INT32): "int32",
    int(VarType.INT64): "int64",
    int(VarType.FP16): "float16",
    int(VarType.FP32): "float32",
    int(VarType.FP64): "float64",
    int(VarType.UINT8): "uint8",
    int(VarType.INT8): "int8",
}

_STRUCTURAL_TYPES = {
    int(VarType.FEED_MINIBATCH), int(VarType.FETCH_LIST),
    int(VarType.STEP_SCOPES), int(VarType.LOD_RANK_TABLE),
    int(VarType.PLACE_LIST), int(VarType.READER), int(VarType.RAW),
}


def _tensor_desc_pb(dtype: str, dims: List[int]) -> bytes:
    body = _field_varint(1, _DTYPE_TO_PROTO.get(dtype, int(VarType.FP32)))
    body += b"".join(_field_varint(2, int(d)) for d in dims)
    return body


def _tensor_desc_from_pb(data: bytes) -> Tuple[int, List[int]]:
    data_type = int(VarType.FP32)
    dims: List[int] = []
    for field, wire, value in _Reader(data).fields():
        if field == 1:
            data_type = value
        elif field == 2:
            dims += [_to_i64(v) for v in _varints_in(value)]
    return data_type, dims


def _var_to_pb(vdesc: Dict) -> bytes:
    vtype = int(vdesc.get("type", int(VarType.LOD_TENSOR)))
    dtype = vdesc.get("dtype", "float32")
    shape = [int(d) for d in vdesc.get("shape", [])]
    type_body = _field_varint(1, vtype)
    td = _tensor_desc_pb(dtype, shape)
    if vtype == int(VarType.SELECTED_ROWS):
        type_body += _field_bytes(2, td)
    elif vtype == int(VarType.LOD_TENSOR_ARRAY):
        type_body += _field_bytes(4, _field_bytes(1, td) + _field_varint(2, 0))
    elif vtype in _STRUCTURAL_TYPES:
        pass  # type enum only
    else:  # LOD_TENSOR and plain dtypes
        type_body += _field_bytes(3, _field_bytes(1, td) + _field_varint(2, 0))
    body = _field_str(1, vdesc["name"])
    body += _field_bytes(2, type_body)
    if vdesc.get("persistable"):
        body += _field_varint(3, 1)
    if vdesc.get("is_data"):
        body += _field_varint(4, 1)  # need_check_feed
    return body


def _var_from_pb(data: bytes) -> Dict:
    out: Dict = {"name": None, "shape": [], "dtype": "float32",
                 "type": int(VarType.LOD_TENSOR), "persistable": False,
                 "stop_gradient": False, "is_data": False}
    for field, wire, value in _Reader(data).fields():
        if field == 1:
            out["name"] = value.decode("utf-8")
        elif field == 2:
            for f2, w2, v2 in _Reader(value).fields():
                if f2 == 1:
                    out["type"] = v2
                elif f2 == 2:  # selected_rows TensorDesc
                    dt, dims = _tensor_desc_from_pb(v2)
                    out["dtype"] = _PROTO_TO_DTYPE.get(dt, "float32")
                    out["shape"] = dims
                elif f2 in (3, 4):  # lod_tensor / tensor_array
                    for f3, w3, v3 in _Reader(v2).fields():
                        if f3 == 1:
                            dt, dims = _tensor_desc_from_pb(v3)
                            out["dtype"] = _PROTO_TO_DTYPE.get(dt, "float32")
                            out["shape"] = dims
        elif field == 3:
            out["persistable"] = bool(value)
        elif field == 4:
            out["is_data"] = bool(value)
    return out


def _op_to_pb(odesc: Dict) -> bytes:
    body = b""
    for slot, names in odesc.get("inputs", {}).items():
        var_body = _field_str(1, slot) + b"".join(_field_str(2, n) for n in names)
        body += _field_bytes(1, var_body)
    for slot, names in odesc.get("outputs", {}).items():
        var_body = _field_str(1, slot) + b"".join(_field_str(2, n) for n in names)
        body += _field_bytes(2, var_body)
    body += _field_str(3, odesc["type"])
    for name in sorted(odesc.get("attrs", {})):
        attr = _attr_to_pb(name, odesc["attrs"][name])
        if attr is not None:
            body += _field_bytes(4, attr)
    return body


def _op_from_pb(data: bytes) -> Dict:
    out: Dict = {"type": None, "inputs": {}, "outputs": {}, "attrs": {}}
    for field, wire, value in _Reader(data).fields():
        if field in (1, 2):
            slot = None
            args: List[str] = []
            for f2, w2, v2 in _Reader(value).fields():
                if f2 == 1:
                    slot = v2.decode("utf-8")
                elif f2 == 2:
                    args.append(v2.decode("utf-8"))
            target = out["inputs"] if field == 1 else out["outputs"]
            if slot is not None:
                target.setdefault(slot, []).extend(args)
        elif field == 3:
            out["type"] = value.decode("utf-8")
        elif field == 4:
            name, v = _attr_from_pb(value)
            if name is not None:
                out["attrs"][name] = v
    return out


def _block_to_pb(bdesc: Dict) -> bytes:
    body = _field_varint(1, bdesc["idx"])
    body += _field_varint(2, bdesc.get("parent_idx", -1))
    for vdesc in bdesc.get("vars", []):
        body += _field_bytes(3, _var_to_pb(vdesc))
    for odesc in bdesc.get("ops", []):
        body += _field_bytes(4, _op_to_pb(odesc))
    fwd = bdesc.get("forward_block_idx", -1)
    if fwd != -1:
        body += _field_varint(5, fwd)
    return body


def _block_from_pb(data: bytes) -> Dict:
    out: Dict = {"idx": 0, "parent_idx": -1, "vars": [], "ops": [],
                 "forward_block_idx": -1, "params": []}
    for field, wire, value in _Reader(data).fields():
        if field == 1:
            out["idx"] = _to_i64(value)
        elif field == 2:
            out["parent_idx"] = _to_i64(value)
        elif field == 3:
            out["vars"].append(_var_from_pb(value))
        elif field == 4:
            out["ops"].append(_op_from_pb(value))
        elif field == 5:
            out["forward_block_idx"] = _to_i64(value)
    return out


def desc_to_pb(desc: Dict, version: int = 0) -> bytes:
    """Serialize a desc-dict (Program._desc_dict form) to ProgramDesc wire bytes."""
    body = b"".join(_field_bytes(1, _block_to_pb(b)) for b in desc["blocks"])
    body += _field_bytes(4, _field_varint(1, version))
    return body


def desc_from_pb(data: bytes) -> Dict:
    out: Dict = {"blocks": [], "version": 0}
    for field, wire, value in _Reader(data).fields():
        if field == 1:
            out["blocks"].append(_block_from_pb(value))
        elif field == 4:
            for f2, w2, v2 in _Reader(value).fields():
                if f2 == 1:
                    out["version"] = _to_i64(v2)
    return out


# ---------------------------------------------------------------------------
# LoDTensor binary stream (tensor_util.cc TensorToStream layout)
# ---------------------------------------------------------------------------

_NP_FROM_PROTO = {
    int(VarType.BOOL): np.dtype("bool"),
    int(VarType.INT16): np.dtype("int16"),
    int(VarType.INT32): np.dtype("int32"),
    int(VarType.INT64): np.dtype("int64"),
    int(VarType.FP16): np.dtype("float16"),
    int(VarType.FP32): np.dtype("float32"),
    int(VarType.FP64): np.dtype("float64"),
    int(VarType.UINT8): np.dtype("uint8"),
    int(VarType.INT8): np.dtype("int8"),
}


def tensor_to_stream(arr: np.ndarray, lod: Optional[List[List[int]]] = None) -> bytes:
    """One LoDTensor record: u32 version, LoD table, u32 version, TensorDesc
    proto (i32-length-prefixed), raw little-endian data."""
    arr = np.ascontiguousarray(arr)
    if str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    out = bytearray()
    out += struct.pack("<I", 0)  # LoDTensor version
    lod = lod or []
    out += struct.pack("<Q", len(lod))
    for level in lod:
        level_arr = np.asarray(level, dtype=np.uint64)
        out += struct.pack("<Q", level_arr.nbytes)
        out += level_arr.tobytes()
    out += struct.pack("<I", 0)  # Tensor version
    desc = _tensor_desc_pb(str(arr.dtype), list(arr.shape))
    out += struct.pack("<i", len(desc))
    out += desc
    out += arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes()
    return bytes(out)


def tensor_from_stream(data: bytes, offset: int = 0):
    """Inverse of tensor_to_stream. Returns (array, lod, next_offset)."""
    (ver,) = struct.unpack_from("<I", data, offset)
    offset += 4
    if ver != 0:
        raise ValueError(f"unsupported LoDTensor version {ver}")
    (lod_levels,) = struct.unpack_from("<Q", data, offset)
    offset += 8
    lod = []
    for _ in range(lod_levels):
        (nbytes,) = struct.unpack_from("<Q", data, offset)
        offset += 8
        level = np.frombuffer(data, dtype="<u8", count=nbytes // 8, offset=offset)
        lod.append(level.tolist())
        offset += nbytes
    (tver,) = struct.unpack_from("<I", data, offset)
    offset += 4
    if tver != 0:
        raise ValueError(f"unsupported Tensor version {tver}")
    (desc_size,) = struct.unpack_from("<i", data, offset)
    offset += 4
    data_type, dims = _tensor_desc_from_pb(data[offset:offset + desc_size])
    offset += desc_size
    dtype = _NP_FROM_PROTO[data_type]
    numel = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(data, dtype=dtype.newbyteorder("<"),
                        count=numel, offset=offset).astype(dtype).reshape(dims)
    offset += numel * dtype.itemsize
    return arr, lod, offset


def save_tensor_file(path: str, arr: np.ndarray,
                     lod: Optional[List[List[int]]] = None) -> None:
    with open(path, "wb") as f:
        f.write(tensor_to_stream(arr, lod))


def load_tensor_file(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    arr, _, _ = tensor_from_stream(data)
    return arr


def save_combine(path: str, named: List[Tuple[str, np.ndarray]]) -> None:
    """save_combine op layout: concatenated LoDTensor streams in input order
    (operators/save_combine_op.h)."""
    with open(path, "wb") as f:
        for _, arr in named:
            f.write(tensor_to_stream(arr))


def load_combine(path: str, names: List[str]) -> Dict[str, np.ndarray]:
    with open(path, "rb") as f:
        data = f.read()
    out = {}
    offset = 0
    for name in names:
        arr, _, offset = tensor_from_stream(data, offset)
        out[name] = arr
    if offset != len(data):
        raise ValueError(
            f"{path}: {len(data) - offset} trailing bytes after reading "
            f"{len(names)} tensors — name list does not match the file")
    return out
