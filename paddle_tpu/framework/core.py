"""Core type system for the TPU-native framework.

Capability parity with the reference's ``paddle/fluid/framework/framework.proto``
(VarType enum at framework.proto:104-137) and ``platform/place.h`` — but instead
of an enum dispatched to per-device CUDA kernels, dtypes map straight to JAX
dtypes and Places map to JAX device sets.
"""
from __future__ import annotations

import dataclasses
import enum
import os as _os

import jax
import jax.numpy as jnp
import numpy as np


class VarType(enum.IntEnum):
    """Variable kinds — mirrors framework.proto:104-137 VarType.Type."""

    # value types (tensor dtypes)
    BOOL = 0
    INT16 = 1
    INT32 = 2
    INT64 = 3
    FP16 = 4
    FP32 = 5
    FP64 = 6
    UINT8 = 20
    INT8 = 21
    BF16 = 22
    # container / structural types
    LOD_TENSOR = 7
    SELECTED_ROWS = 8
    FEED_MINIBATCH = 9
    FETCH_LIST = 10
    STEP_SCOPES = 11
    LOD_RANK_TABLE = 12
    LOD_TENSOR_ARRAY = 13
    PLACE_LIST = 14
    READER = 15
    RAW = 17
    TUPLE = 18


_DTYPE_TO_VARTYPE = {
    "bool": VarType.BOOL,
    "int16": VarType.INT16,
    "int32": VarType.INT32,
    "int64": VarType.INT64,
    "float16": VarType.FP16,
    "float32": VarType.FP32,
    "float64": VarType.FP64,
    "uint8": VarType.UINT8,
    "int8": VarType.INT8,
    "bfloat16": VarType.BF16,
}
_VARTYPE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_VARTYPE.items()}


def convert_dtype(dtype) -> str:
    """Normalize any dtype spec (str / np / jnp / VarType) to a canonical string."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, VarType):
        return _VARTYPE_TO_DTYPE[dtype]
    if isinstance(dtype, int):   # raw proto enum value (framework.proto:91)
        return _VARTYPE_TO_DTYPE[VarType(dtype)]
    if isinstance(dtype, str):
        if dtype in _DTYPE_TO_VARTYPE:
            return dtype
        return np.dtype(dtype).name
    if dtype in (jnp.bfloat16,):
        return "bfloat16"
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    return name


_X64_NARROW = {"int64": "int32", "uint64": "uint32", "float64": "float32"}


def dtype_to_jax(dtype) -> jnp.dtype:
    """Compute dtype for a declared var dtype. Serialization keeps the
    declared width (VarType in the protobuf desc); compute canonicalizes
    64-bit types to what jax actually runs without x64 — silently, instead
    of per-op truncation warnings on every int64 astype."""
    s = convert_dtype(dtype)
    if s == "bfloat16":
        return jnp.bfloat16
    import jax

    if not jax.config.jax_enable_x64 and s in _X64_NARROW:
        s = _X64_NARROW[s]
    return jnp.dtype(s)


def int_index_dtype() -> jnp.dtype:
    """The int64-declared index dtype as jax will actually carry it."""
    return dtype_to_jax("int64")


def dtype_is_floating(dtype) -> bool:
    return convert_dtype(dtype) in ("float16", "float32", "float64", "bfloat16")


# ---------------------------------------------------------------------------
# Places — reference platform/place.h. On the TPU build a Place names a JAX
# backend; `XLAPlace` is the canonical accelerator place.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Place:
    backend: str = "default"
    device_id: int = 0

    def jax_device(self):
        if self.backend == "default":
            return jax.devices()[self.device_id]
        return jax.devices(self.backend)[self.device_id]

    def __repr__(self):  # pragma: no cover
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__(backend="cpu", device_id=device_id)


class XLAPlace(Place):
    """The accelerator place: whatever JAX's default backend exposes (TPU)."""

    def __init__(self, device_id: int = 0):
        super().__init__(backend="default", device_id=device_id)


# Alias so reference scripts that say CUDAPlace keep working on TPU.
TPUPlace = XLAPlace


class BackwardStrategy:
    """Dygraph backward knobs — reference pybind/imperative.cc:491-519
    (``core.BackwardStrategy`` with the ``sort_sum_gradient`` property).

    ``sort_sum_gradient=True`` asks the reference's BasicEngine to sum a
    var's repeated gradients in a deterministic (sorted) order.  The tape
    engine here replays in reverse record order, which is already
    deterministic by construction, so the flag is accepted for API parity
    and does not change behavior."""

    def __init__(self):
        self.sort_sum_gradient = False


def is_compiled_with_tpu() -> bool:
    return any(d.platform not in ("cpu",) for d in jax.devices())


# ---------------------------------------------------------------------------
# Global flags registry — reference platform/flags.cc (gflags). Most reference
# flags control allocator/cudnn behavior that XLA owns; we keep the registry so
# `fluid.set_flags`/`get_flags` style code works and a few flags are live.
# ---------------------------------------------------------------------------

_GLOBAL_FLAGS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_check_nan_inf_level": "fetch",  # "fetch" | "op" (eager per-op scan)
    "FLAGS_benchmark": False,
    # steady-state dispatch record in Executor.run (framework/executor.py):
    # after the first step a (program, feed-sig, fetch) record skips feed
    # re-normalization and cache-key rebuild. False = always take the
    # full (pre-record) path; used for A/B in tools/dispatch_bench.py.
    "FLAGS_dispatch_fast_path": True,
    # opt-in flat-buffer fused optimizer sweep (optimizer.py
    # apply_gradients): one fused update op per (dtype, hparam-signature)
    # parameter group with moments in a flat megabuffer layout, instead of
    # one update op per parameter. Equivalent to passing fuse=True to the
    # optimizer constructor; see docs/memory_levers.md.
    "FLAGS_fuse_optimizer": False,
    # lower each fused flat-buffer optimizer group through ONE Pallas
    # megakernel launch (ops/pallas_kernels._opt_megakernel) instead of
    # the XLA elementwise-fusion stream the attribution ranks as the
    # optimizer residue tail. None = auto (on on TPU, off elsewhere —
    # interpret mode would only slow the CPU lane); True/False forces.
    # Only reached when the flat sweep itself is on (fuse=True /
    # FLAGS_fuse_optimizer). See docs/kernels.md.
    "FLAGS_fuse_optimizer_pallas": None,
    # persistent XLA compilation cache directory ('' = disabled). When set,
    # repeated processes compiling the same program hit the on-disk cache
    # instead of paying the cold XLA compile (jax_compilation_cache_dir).
    "FLAGS_compile_cache_dir": _os.environ.get("FLAGS_compile_cache_dir", ""),
    # program-report JSONL sink ('' = disabled): every compiled executable
    # writes one cost/memory introspection record under this directory
    # (observability/program_report.py; see docs/observability.md)
    "FLAGS_program_report_dir": _os.environ.get(
        "FLAGS_program_report_dir", ""),
    # quantized wire payload for fluid SUM-collectives ('' = off,
    # "bf16" | "int8"): c_allreduce_sum/avg and c_reducescatter reroute
    # through the chunk-scaled quantized exchange (f32 accumulation) in
    # paddle_tpu/parallel/comm_opt.py — the GradientMergeOptimizer k-step
    # tail reduction and transpiled dp gradient sync included. See
    # docs/comm_opt.md.
    "FLAGS_collective_comm_dtype": _os.environ.get(
        "FLAGS_collective_comm_dtype", ""),
    # Program IR static verifier (paddle_tpu/analysis/, see
    # docs/static_analysis.md): when on, Executor.run lints each program
    # once per version BEFORE compiling it — error-severity findings
    # raise, warnings log. Never touches the dispatch fast path.
    "FLAGS_check_program": bool(int(_os.environ.get(
        "FLAGS_check_program", "0") or 0)),
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_allocator_strategy": "xla_managed",
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_use_system_allocator": False,
    "FLAGS_executor_log_deps": False,
    # roi_align adaptive sampling: False = bounded uniform grid (fast
    # default), True = exact reference ceil(roi/pooled) per-ROI density
    # via a weighted static super-grid (ops/detection.py roi_align)
    "FLAGS_roi_align_exact": False,
    # multiplier on the exact-mode grid bound for ROIs larger than the
    # feature map (unclipped proposals); 1 = image-derived bound
    "FLAGS_roi_align_exact_scale": 1,
}


def set_flags(flags: dict):
    for k, v in flags.items():
        _GLOBAL_FLAGS[k] = v
    if flags.get("FLAGS_compile_cache_dir"):
        ensure_compile_cache()


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _GLOBAL_FLAGS.get(k) for k in flags}


def get_flag(name, default=None):
    return _GLOBAL_FLAGS.get(name, default)


def flags_snapshot() -> dict:
    """Copy of the full flag state (anomaly forensics dumps record it)."""
    return dict(_GLOBAL_FLAGS)


# ---------------------------------------------------------------------------
# Persistent XLA compilation cache (FLAGS_compile_cache_dir). The reference
# pays every XLA compile from scratch per process; jax's on-disk cache
# (jax_compilation_cache_dir) makes the second process a deserialize instead
# of a compile. Hit/miss counters come from jax.monitoring events so the
# Executor can log and RecordEvent whether a compile was served from disk.
# ---------------------------------------------------------------------------

_compile_cache_state = {"dir": None, "hits": 0, "misses": 0, "listener": False}


def _compile_cache_listener(event, **kwargs):
    if event == "/jax/compilation_cache/cache_hits":
        _compile_cache_state["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _compile_cache_state["misses"] += 1


def ensure_compile_cache() -> bool:
    """Point jax's persistent compilation cache at FLAGS_compile_cache_dir.

    Idempotent; returns True when the cache is active. The size thresholds
    are dropped to zero so even small programs (which this framework compiles
    per (program, feed-sig, fetch) key) are cached across processes.
    """
    d = _GLOBAL_FLAGS.get("FLAGS_compile_cache_dir")
    if not d:
        return False
    if _compile_cache_state["dir"] != d:
        if not _compile_cache_state["listener"]:
            from jax import monitoring

            monitoring.register_event_listener(_compile_cache_listener)
            _compile_cache_state["listener"] = True
        jax.config.update("jax_compilation_cache_dir", str(d))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _compile_cache_state["dir"] = d
    return True


def compile_cache_counters():
    """(hits, misses) served by the persistent cache in this process."""
    return _compile_cache_state["hits"], _compile_cache_state["misses"]
