"""Static-graph Program IR.

Capability parity with the reference's Python IR mirror
(python/paddle/fluid/framework.py: Program:3852, Block:2391, Operator:1822,
Variable:835, Parameter:4962) over the C++ desc layer
(paddle/fluid/framework/framework.proto). Here the IR is Python-native and
JSON-serializable; execution compiles whole Blocks to XLA (see executor.py)
instead of interpreting per-op kernels.
"""
from __future__ import annotations

import contextlib
import copy
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from . import unique_name
from .core import VarType, convert_dtype

GRAD_SUFFIX = "@GRAD"


class Variable:
    """A named tensor slot in a Block — reference framework.py:835.

    ``shape`` may contain -1 (unknown / batch dims); actual shapes are fixed at
    Executor compile time from feed shapes, since XLA requires static shapes.
    """

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape=None,
        dtype="float32",
        type: VarType = VarType.LOD_TENSOR,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        need_check_feed: bool = False,
        initializer=None,
        sharding=None,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else ()
        self.dtype = convert_dtype(dtype)
        self.type = type
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.need_check_feed = need_check_feed
        # Optional initializer record (consumed when building startup programs).
        self.initializer = initializer
        # GSPMD-style PartitionSpec annotation: per-dim axis-name tuple
        # (None = replicated dim), set by sharding.shard_tensor / the
        # propagation pass, consumed by the executor's gspmd mode and
        # persisted through the desc round-trip (paddle_tpu/sharding/).
        self.sharding = tuple(sharding) if sharding is not None else None
        # op that produced it last (filled lazily when needed)

    # -- info helpers -------------------------------------------------------
    @property
    def grad_name(self) -> str:
        return self.name + GRAD_SUFFIX

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def astype(self, dtype):
        from ..layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    def _desc_dict(self):
        d = {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "type": int(self.type),
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
        }
        if getattr(self, "sharding", None) is not None:
            # only annotated vars carry the key: unannotated programs'
            # descs (and fingerprints) stay byte-stable
            from ..sharding.spec import spec_to_json

            d["sharding"] = spec_to_json(self.sharding)
        return d

    def __repr__(self):
        return (
            f"Var({self.name}: shape={list(self.shape)}, dtype={self.dtype}, "
            f"{'persistable, ' if self.persistable else ''}"
            f"stop_gradient={self.stop_gradient})"
        )

    # Operator sugar so `a + b` works in static graph mode (reference patches
    # these via monkey-patching in math_op_patch.py).
    def _binary(self, other, fn_name, reverse=False):
        from ..layers import math_op_patch

        return math_op_patch.binary_op(self, other, fn_name, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __radd__(self, other):
        return self._binary(other, "elementwise_add", reverse=True)

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __rmul__(self, other):
        return self._binary(other, "elementwise_mul", reverse=True)

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __neg__(self):
        from ..layers import tensor as tensor_layers

        return tensor_layers.scale(self, scale=-1.0)

    def __eq__(self, other):
        return self is other

    def __hash__(self):
        return id(self)


class Parameter(Variable):
    """Trainable persistable variable — reference framework.py:4962."""

    def __init__(self, block, shape, dtype, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        kwargs["persistable"] = True
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.stop_gradient = not self.trainable

    def __repr__(self):
        return f"Param({self.name}: shape={list(self.shape)}, dtype={self.dtype})"


class Operator:
    """One op node — reference framework.py:1822 / framework.proto OpDesc.

    inputs/outputs map slot name -> list of variable names; attrs are plain
    python values (scalars, lists, strings, or int block indices for control
    flow sub-blocks).
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = _normalize_io(inputs)
        self.outputs: Dict[str, List[str]] = _normalize_io(outputs)
        self.attrs: Dict[str, Any] = dict(attrs or {})
        # op_role stamped at creation so EVERY insertion path (append_op,
        # _insert_op, _prepend_op, transpilers) shares it; deserialization
        # keeps the persisted role (already present in attrs)
        role = getattr(getattr(block, "program", None),
                       "_current_op_role", 0)
        if role and "op_role" not in self.attrs:
            self.attrs["op_role"] = role

    # -- accessors ----------------------------------------------------------
    def input(self, slot) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for names in self.inputs.values() for n in names]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for names in self.outputs.values() for n in names]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def has_attr(self, name):
        return name in self.attrs

    def _desc_dict(self):
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": copy.deepcopy(self.attrs),
        }

    def __repr__(self):
        ins = ", ".join(f"{k}={v}" for k, v in self.inputs.items())
        outs = ", ".join(f"{k}={v}" for k, v in self.outputs.items())
        return f"{{{self.type}: ({ins}) -> ({outs})}}"


def _normalize_io(io: Optional[Dict[str, Any]]) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = OrderedDict()
    if not io:
        return out
    for slot, vals in io.items():
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        names = []
        for v in vals:
            if isinstance(v, Variable):
                names.append(v.name)
            elif isinstance(v, str):
                names.append(v)
            else:
                raise TypeError(f"bad i/o entry for slot {slot}: {v!r}")
        out[slot] = names
    return out


class Block:
    """A straight-line list of ops + a var table — reference framework.py:2391."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        # sub-block chaining for backward (grad block of a forward sub-block)
        self.forward_block_idx = -1
        self.vars: "OrderedDict[str, Variable]" = OrderedDict()
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- var management -----------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        shape = kwargs.pop("shape")
        dtype = kwargs.pop("dtype", "float32")
        global_block = self.program.global_block()
        param = Parameter(global_block, shape=shape, dtype=dtype, **kwargs)
        global_block.vars[param.name] = param
        return param

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"Variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _var_recursive(self, name: str) -> Variable:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise ValueError(f"Variable {name!r} not found in block hierarchy")

    def _has_var_recursive(self, name: str) -> bool:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return True
            blk = blk.parent_block
        return False

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- op management ------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self.program._bump_version()
        self._infer_shape(op)
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        self._infer_shape(op)
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        return self._insert_op(0, type, inputs=inputs, outputs=outputs, attrs=attrs)

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def _infer_shape(self, op: Operator):
        from .registry import infer_shape_for_op

        infer_shape_for_op(self, op)

    def _desc_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v._desc_dict() for v in self.vars.values()],
            "params": [v.name for v in self.vars.values() if isinstance(v, Parameter)],
            "ops": [op._desc_dict() for op in self.ops],
        }

    def __repr__(self):
        lines = [f"Block {self.idx} (parent {self.parent_idx}):"]
        lines += [f"  {op}" for op in self.ops]
        return "\n".join(lines)


class Program:
    """A whole program: a tree of Blocks — reference framework.py:3852."""

    # OpRole values — wire parity with framework.proto OpRole / the
    # reference's op_role attr (op_proto_maker.h:27)
    OP_ROLE_FORWARD = 0
    OP_ROLE_BACKWARD = 1
    OP_ROLE_OPTIMIZE = 2
    OP_ROLE_RPC = 4
    OP_ROLE_DIST = 8
    OP_ROLE_LRSCHED = 16
    OP_ROLE_LOSS = 0x100          # OR'd onto Forward/Backward on the loss op
    OP_ROLE_NOT_SPECIFIED = 0x1000

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._seed_counter = 0
        # list of (feed_name,) / fetch info filled by io helpers
        self._is_start_up_program = False
        self._pass_applied = []
        # distributed annotations (filled by fleet/transpilers)
        self._annotations: Dict[str, Any] = {}
        self._current_op_role = Program.OP_ROLE_FORWARD

    @contextlib.contextmanager
    def op_role_guard(self, role: int):
        """Ops appended inside the guard carry attrs['op_role'] = role
        (reference program._optimized_guard / _backward_role_guard) —
        clone(for_test=True) strips non-forward roles."""
        prev = self._current_op_role
        self._current_op_role = role
        try:
            yield
        finally:
            self._current_op_role = prev

    # -- block management ---------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, new_idx, parent_idx=parent)
        self.blocks.append(blk)
        self.current_block_idx = new_idx
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # -- parameters ---------------------------------------------------------
    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    # -- cloning ------------------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        p = Program.__new__(Program)
        p.blocks = []
        p.current_block_idx = 0
        p.random_seed = self.random_seed
        p._seed_counter = self._seed_counter
        p._is_start_up_program = self._is_start_up_program
        p._pass_applied = list(self._pass_applied)
        p._annotations = copy.deepcopy(self._annotations)
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            nb.forward_block_idx = blk.forward_block_idx
            p.blocks.append(nb)
        for blk, nb in zip(self.blocks, p.blocks):
            for v in blk.vars.values():
                if isinstance(v, Parameter):
                    nv = Parameter(
                        nb,
                        shape=v.shape,
                        dtype=v.dtype,
                        name=v.name,
                        trainable=v.trainable,
                        optimize_attr=copy.deepcopy(v.optimize_attr),
                        regularizer=v.regularizer,
                        is_distributed=v.is_distributed,
                    )
                    nv.stop_gradient = v.stop_gradient
                else:
                    nv = Variable(
                        nb,
                        name=v.name,
                        shape=v.shape,
                        dtype=v.dtype,
                        type=v.type,
                        persistable=v.persistable,
                        stop_gradient=v.stop_gradient,
                        is_data=v.is_data,
                    )
                nv.sharding = getattr(v, "sharding", None)
                nb.vars[nv.name] = nv
            for op in blk.ops:
                if for_test and op.attr("is_test_skip", False):
                    continue
                # drop backward/optimize/lr-sched ops — op_role is a BITMASK
                # (reference op_proto_maker.h: Loss=0x100 ORs onto Forward, so
                # a loss op stamped Forward|Loss=256 must survive the clone);
                # prune only when a backward/optimize/lr-sched bit is set,
                # mirroring the reference's _is_backward_op/_is_optimize_op
                if for_test and int(op.attr("op_role", 0) or 0) & (
                        Program.OP_ROLE_BACKWARD | Program.OP_ROLE_OPTIMIZE |
                        Program.OP_ROLE_LRSCHED):
                    continue
                nop = Operator(
                    nb,
                    type=op.type,
                    inputs={k: list(v) for k, v in op.inputs.items()},
                    outputs={k: list(v) for k, v in op.outputs.items()},
                    attrs=copy.deepcopy(op.attrs),
                )
                if for_test and "is_test" in _TEST_MODE_ATTR_OPS.get(op.type, ()):
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
        return p

    def _bump_version(self):
        self._mutation_counter = getattr(self, "_mutation_counter", 0) + 1

    def _version_token(self):
        """Cheap mutation token for executor compile caching: counts every
        append/insert/remove/attr-set (the executor also holds a strong ref to
        the program, so id() cannot be reused while an entry is cached)."""
        return (
            getattr(self, "_mutation_counter", 0),
            tuple((len(b.ops), len(b.vars)) for b in self.blocks),
        )

    def _fingerprint(self) -> str:
        """Stable hash of the full desc for executor compile caching."""
        import hashlib
        import json

        payload = json.dumps(
            [b._desc_dict() for b in self.blocks], sort_keys=True, default=str
        )
        return hashlib.sha1(payload.encode()).hexdigest()

    def _desc_dict(self):
        d = {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b._desc_dict() for b in self.blocks],
        }
        # sharding-relevant annotations ride the desc (mesh plan + the
        # explicit annotation seed set) so annotated programs survive the
        # save/load round-trip; absent on unannotated programs
        ann = {k: self._annotations[k]
               for k in ("mesh", "sharding_annotated")
               if self._annotations.get(k) is not None}
        if ann:
            d["annotations"] = ann
        return d

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)


# ops whose behavior flips in test mode (dropout/batch_norm) — used by clone(for_test)
_TEST_MODE_ATTR_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
    "sync_batch_norm": ("is_test",),
    # eval must stop mutating the moving quantization-scale state
    "fake_quantize_dequantize_moving_average_abs_max": ("is_test",),
    "cudnn_lstm": ("is_test",),
}


# ---------------------------------------------------------------------------
# Default program stack — reference framework.py:5163-5330
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()
_startup_program_._is_start_up_program = True


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


_name_scope_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix: str):
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()
