"""ParamAttr — parity with python/paddle/fluid/param_attr.py."""
from __future__ import annotations

from typing import Optional


class ParamAttr:
    def __init__(
        self,
        name: Optional[str] = None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        do_model_average: bool = False,
        gradient_clip=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.gradient_clip = gradient_clip

    @staticmethod
    def _to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            return ParamAttr(trainable=arg) if arg else ParamAttr(trainable=False)
        # an Initializer instance
        return ParamAttr(initializer=arg)

    def _to_kwargs(self, with_initializer=False):
        kw = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "do_model_average": self.do_model_average,
        }
        if with_initializer:
            kw["initializer"] = self.initializer
        return kw


WeightNormParamAttr = ParamAttr  # capability placeholder (weight-norm reparam TBD)
