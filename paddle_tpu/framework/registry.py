"""Op registry: declarative op specs with JAX lowerings.

Replaces the reference's static kernel registry
(paddle/fluid/framework/op_registry.h:223-291 REGISTER_OPERATOR /
REGISTER_OP_*_KERNEL + op_info.h OpInfoMap). Instead of per-(place,dtype,layout)
kernels, each op registers ONE lowering function Block-op -> jax computation;
XLA specializes for device/dtype. Grad ops are first-class IR ops (parity with
GradOpDescMakerBase, grad_op_desc_maker.h); by default the grad lowering is the
jax.vjp of the forward lowering — whole-program XLA CSE removes the replayed
forward, so this costs nothing after fusion.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from .core import dtype_to_jax, dtype_is_floating

GRAD_SUFFIX = "@GRAD"

# ---------------------------------------------------------------------------
# Spec + registry
# ---------------------------------------------------------------------------

LowerFn = Callable[["LowerCtx", "Operator", Dict[str, List[Any]]], Dict[str, List[Any]]]


@dataclasses.dataclass
class OpSpec:
    type: str
    lower: LowerFn
    # shape inference for build-time metadata; None -> eval_shape fallback
    infer_shape: Optional[Callable] = None
    # 'auto' = default vjp-backed grad op; None = non-differentiable;
    # callable(op, block, grad_map) -> list[Operator-descs] = custom maker
    grad: Any = "auto"
    # slots eligible for gradients (None = every floating-point input slot)
    diff_inputs: Optional[Sequence[str]] = None
    # slots whose inputs are NOT needed by the default grad lowering replay
    needs_rng: bool = False
    # op mutates persistable state (optimizer ops) — affects executor outputs
    is_optimizer: bool = False
    # GSPMD-style sharding propagation rule (paddle_tpu/sharding/rules.py):
    # fn(RuleCtx) derives/refines PartitionSpecs for the op's vars in both
    # directions. None -> the propagation pass falls back to conservative
    # replication (and reports the coverage gap).
    sharding_rule: Optional[Callable] = None


_OPS: Dict[str, OpSpec] = {}


def register_op(type: str, **kwargs):
    """Decorator: @register_op("relu") def _(ctx, op, ins): ..."""

    def deco(fn: LowerFn):
        _OPS[type] = OpSpec(type=type, lower=fn, **kwargs)
        return fn

    return deco


def get_op_spec(type: str) -> OpSpec:
    spec = _OPS.get(type)
    if spec is None:
        if type.endswith("_grad") and type[: -len("_grad")] in _OPS:
            return _generic_grad_spec(type)
        raise NotImplementedError(f"op {type!r} has no registered lowering")
    return spec


def has_op(type: str) -> bool:
    return type in _OPS or (type.endswith("_grad") and type[: -len("_grad")] in _OPS)


def all_op_types() -> List[str]:
    return sorted(_OPS)


# ---------------------------------------------------------------------------
# Lowering context
# ---------------------------------------------------------------------------


class LowerCtx:
    """Carried through a Block lowering: the value environment and ambient state.

    env maps var name -> jax value. Mesh/axis info is used by collective ops
    (c_allreduce_* etc.) which lower to lax.p* over named mesh axes — the
    TPU-native replacement for NCCL ring_ids (platform/collective_helper.h).
    """

    # Monotone count of rng-key consumptions across ALL contexts (sub-block
    # contexts included).  The executor samples it around a block trace to
    # learn whether the program consumes randomness at all; rng-free programs
    # then skip the per-step fold_in on the dispatch fast path.  Races can
    # only over-count (another thread tracing concurrently), which degrades
    # to the safe per-step fold_in — never to key reuse.
    rng_use_count: int = 0

    def __init__(self, program, block, env, rng_key=None, mesh_axes=None, is_test=False):
        self.program = program
        self.block = block
        self.env: Dict[str, Any] = env
        self._rng_key = rng_key
        self._rng_counter = 0
        # ring_id -> mesh axis name mapping for collectives
        self.mesh_axes: Dict[int, str] = mesh_axes or {}
        self.is_test = is_test

    def next_rng(self, salt: int = 0):
        LowerCtx.rng_use_count += 1
        if self._rng_key is None:
            # deterministic fallback (e.g. shape inference)
            self._rng_key = jax.random.PRNGKey(0)
        key = jax.random.fold_in(self._rng_key, self._rng_counter * 1000003 + salt)
        self._rng_counter += 1
        return key

    def rng_for(self, op):
        """Deterministic key derived from the op's output names.

        Grad-op vjp replay of a random forward op re-derives the SAME key (the
        fake forward op carries the original output names), so the replayed
        randomness is bit-identical and XLA CSE merges it with the forward.
        Recompute re-emission (backward.py) renames outputs but pins the
        original names in the ``__rng_names__`` attr so the recomputed
        randomness (e.g. a dropout mask) matches the forward exactly.
        """
        import zlib

        LowerCtx.rng_use_count += 1
        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(0)
        names = op.attr("__rng_names__") if hasattr(op, "attr") else None
        if not names:
            names = [n for ns in op.outputs.values() for n in ns]
        salt = zlib.crc32(("|".join(sorted(names))).encode()) & 0x7FFFFFFF
        return jax.random.fold_in(self._rng_key, salt)

    def axis_name(self, ring_id: int) -> Optional[str]:
        return self.mesh_axes.get(ring_id)


def _op_scope_name(op) -> str:
    """Stable trace-scope identity for one IR op: `ptop_<type>__<out>`.

    run_lowering wraps every lowering in jax.named_scope with this name, so
    the op identity rides into XLA's HLO metadata (op_name) and the device
    profiler's measured per-instruction times can be attributed back to IR
    ops (utils/device_trace.py — the reference's device_tracer.cc
    correlation id serves the same purpose)."""
    first_out = next((n for ns in op.outputs.values() for n in ns
                      if n and n != "@EMPTY@"), "")
    raw = f"ptop_{op.type}__{first_out}"
    return "".join(c if c.isalnum() or c == "_" else "_" for c in raw)


def run_lowering(ctx: LowerCtx, op) -> None:
    """Execute one op's lowering against ctx.env (in place)."""
    spec = get_op_spec(op.type)
    ins = {
        slot: [ctx.env[n] for n in names]
        for slot, names in op.inputs.items()
        if all(n in ctx.env for n in names)
    }
    with jax.named_scope(_op_scope_name(op)):
        outs = spec.lower(ctx, op, ins)
    _bind_outputs(ctx.env, op, outs)


def _bind_outputs(env, op, outs: Dict[str, Any]):
    for slot, vals in outs.items():
        names = op.outputs.get(slot, [])
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            if val is not None and name != "@EMPTY@":
                env[name] = val


# ---------------------------------------------------------------------------
# Generic vjp-backed grad op
# ---------------------------------------------------------------------------


def _generic_grad_spec(grad_type: str) -> OpSpec:
    fwd_type = grad_type[: -len("_grad")]
    fwd_spec = _OPS[fwd_type]

    def lower_grad(ctx: LowerCtx, op, ins):
        return lower_vjp_grad(ctx, op, ins, fwd_spec)

    return OpSpec(type=grad_type, lower=lower_grad, grad=None)


class _FakeOp:
    """Light op stand-in so a grad lowering can replay the forward lowering."""

    __slots__ = ("type", "inputs", "outputs", "attrs", "block")

    def __init__(self, type, inputs, outputs, attrs, block):
        self.type = type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs
        self.block = block

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])


def lower_vjp_grad(ctx: LowerCtx, op, ins, fwd_spec: OpSpec):
    """Default grad lowering: jax.vjp of the forward lowering.

    The grad op (built by the default grad maker in backward.py) carries the
    forward op's desc in attrs['__fwd__']: {type, inputs, outputs, attrs}.
    Its inputs hold the forward inputs under their original slots plus the
    output grads under '<slot>@GRAD'; outputs are '<slot>@GRAD' per fwd input.
    """
    fwd = op.attrs["__fwd__"]
    fwd_inputs: Dict[str, List[str]] = fwd["inputs"]
    fwd_outputs: Dict[str, List[str]] = fwd["outputs"]

    fake = _FakeOp(fwd["type"], fwd_inputs, fwd_outputs, dict(fwd["attrs"]), ctx.block)

    # Which input slots are differentiable?
    if fwd_spec.diff_inputs is not None:
        diff_slots = [s for s in fwd_spec.diff_inputs if s in fwd_inputs]
    else:
        diff_slots = []
        for slot, names in fwd_inputs.items():
            vals = [ctx.env[n] for n in names if n in ctx.env]
            if vals and all(jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) for v in vals):
                diff_slots.append(slot)
    # only produce grads the op actually asks for
    diff_slots = [s for s in diff_slots if (s + GRAD_SUFFIX) in op.outputs]

    const_ins = {
        slot: [ctx.env[n] for n in names]
        for slot, names in fwd_inputs.items()
        if slot not in diff_slots and all(n in ctx.env for n in names)
    }
    diff_ins = {slot: [ctx.env[n] for n in fwd_inputs[slot]] for slot in diff_slots}

    # Deterministic rng replay: reuse the forward op's rng salt so XLA CSE can
    # dedupe the recomputed forward against the original forward computation.
    salt = fwd["attrs"].get("__rng_salt__", 0)
    saved_counter = ctx._rng_counter

    def fwd_fn(d_ins):
        ctx._rng_counter = saved_counter  # stable keys across vjp traces
        merged = dict(const_ins)
        merged.update(d_ins)
        outs = fwd_spec.lower(ctx, fake, merged)
        flat = []
        for oslot in sorted(fwd_outputs):
            names = fwd_outputs[oslot]
            vals = outs.get(oslot)
            if vals is None:
                vals = [None] * len(names)
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for v in vals:
                flat.append(v)
        return flat

    primal_flat, vjp_fn = jax.vjp(fwd_fn, diff_ins)

    # Assemble cotangents for every forward output, zeros where no grad flows.
    cotangents = []
    i = 0
    for oslot in sorted(fwd_outputs):
        for name in fwd_outputs[oslot]:
            gname = None
            # grad op convention: out-grad input slot is '<oslot>@GRAD'
            gslot = oslot + GRAD_SUFFIX
            if gslot in op.inputs:
                idx = fwd_outputs[oslot].index(name)
                if idx < len(op.inputs[gslot]):
                    gname = op.inputs[gslot][idx]
            if gname is not None and gname in ctx.env:
                g = ctx.env[gname]
            else:
                p = primal_flat[i]
                g = jnp.zeros_like(p) if p is not None else None
            cotangents.append(g)
            i += 1

    # jax.vjp requires non-None cotangents matching primal structure; under
    # AMP a consumer computing in f32 can hand back an f32 cotangent for a
    # bf16 output — align dtypes to the primal (the cast is exact f32<-bf16)
    cotangents = [
        jnp.zeros_like(p) if (g is None and p is not None)
        else (g.astype(p.dtype) if (g is not None and p is not None
                                    and g.dtype != p.dtype) else g)
        for g, p in zip(cotangents, primal_flat)
    ]
    (grads,) = vjp_fn(cotangents)

    out: Dict[str, Any] = {}
    for slot in diff_slots:
        out[slot + GRAD_SUFFIX] = grads[slot]
    return out


# ---------------------------------------------------------------------------
# Build-time shape inference
# ---------------------------------------------------------------------------

_DYN = 97  # stand-in extent for -1 dims during eval_shape (prime, unlikely real)


def set_sharding_rule(op_type: str, fn) -> None:
    """Attach (or replace) an op's sharding-propagation rule after
    registration — the sibling of :func:`set_infer_shape` for the
    GSPMD-style propagation pass (paddle_tpu/sharding/).  Rules for the
    built-in op families live in sharding/rules.py and register through
    exactly this hook."""
    spec = _OPS[op_type]
    _OPS[op_type] = dataclasses.replace(spec, sharding_rule=fn)


def get_sharding_rule(op_type: str) -> Optional[Callable]:
    """The registered rule for ``op_type`` (grad ops resolve through
    their forward spec only if explicitly registered; the propagation
    pass has a generic grad tie-rule instead)."""
    spec = _OPS.get(op_type)
    return spec.sharding_rule if spec is not None else None


def set_infer_shape(op_type: str, fn) -> None:
    """Attach (or replace) an op's declared infer_shape after registration
    — the hook the analysis shape checker's ``no_inference`` findings ask
    op authors to use when the eval_shape fallback cannot abstract a
    lowering (data-dependent output shapes, host-materializing ops)."""
    spec = _OPS[op_type]
    _OPS[op_type] = dataclasses.replace(spec, infer_shape=fn)


def _copy_meta(block, out_name, shape, dtype) -> None:
    if out_name and out_name != "@EMPTY@" and \
            block._has_var_recursive(out_name):
        var = block._var_recursive(out_name)
        var.shape = tuple(shape)
        var.dtype = dtype


def infer_identity(in_slot: str = "X", out_slot: str = "Out"):
    """Declared infer_shape: every ``out_slot`` output takes the first
    ``in_slot`` input's shape/dtype. Correct for unary math, activations,
    scale/clip/sum, and the paddle elementwise family (Y broadcasts INTO
    X's shape, so Out always has X's metadata). Declared specs also skip
    the per-append eval_shape trace — program builds get cheaper."""

    def infer(block, op):
        names = op.inputs.get(in_slot) or []
        if not names or not block._has_var_recursive(names[0]):
            return
        src = block._var_recursive(names[0])
        for out_name in op.outputs.get(out_slot, []):
            _copy_meta(block, out_name, src.shape, src.dtype)

    return infer


def infer_cast(block, op):
    """cast: X's shape, attr-declared dtype."""
    from .core import convert_dtype

    names = op.inputs.get("X") or []
    if not names or not block._has_var_recursive(names[0]):
        return
    src = block._var_recursive(names[0])
    dtype = convert_dtype(op.attr("out_dtype", src.dtype))
    for out_name in op.outputs.get("Out", []):
        _copy_meta(block, out_name, src.shape, dtype)


def infer_dynamic(out_dims: Dict[str, int], dtypes: Optional[Dict[str, str]]
                  = None, like_slot: str = "X"):
    """Declared infer_shape for data-dependent ops (unique, where_index …)
    whose output extents only exist at run time: declare rank-correct
    all--1 shapes per output slot so downstream build-time inference sees
    honest unknowns instead of stale/empty metadata. ``dtypes`` pins
    output dtypes; slots absent from it inherit the ``like_slot`` input's
    dtype."""

    def infer(block, op):
        names = op.inputs.get(like_slot) or []
        src_dtype = None
        if names and block._has_var_recursive(names[0]):
            src_dtype = block._var_recursive(names[0]).dtype
        for slot, rank in out_dims.items():
            dtype = (dtypes or {}).get(slot) or src_dtype
            if dtype is None:
                continue
            for out_name in op.outputs.get(slot, []):
                _copy_meta(block, out_name, (-1,) * rank, dtype)

    return infer


def infer_shape_for_op(block, op) -> None:
    """Fill output Variable shapes/dtypes at graph-build time.

    Uses the op's registered infer_shape if present, else jax.eval_shape over
    the lowering with -1 dims substituted; -1 is restored on dims that come
    back as the stand-in extent. This is metadata only — executor compilation
    re-traces with real feed shapes.
    """
    try:
        spec = get_op_spec(op.type)
    except NotImplementedError:
        return
    if spec.infer_shape is not None:
        spec.infer_shape(block, op)
        return
    if op.type.endswith("_grad"):
        _infer_grad_shapes(block, op)
        return

    try:
        slots, flat = [], []
        for slot, names in op.inputs.items():
            for n in names:
                v = block._var_recursive(n)
                shape = tuple(_DYN if d == -1 else d for d in v.shape)
                slots.append(slot)
                flat.append(jax.ShapeDtypeStruct(shape, dtype_to_jax(v.dtype)))

        def f(*args):
            ins: Dict[str, List[Any]] = {}
            for slot, val in zip(slots, args):
                ins.setdefault(slot, []).append(val)
            ctx = LowerCtx(block.program, block, {})
            return spec.lower(ctx, op, ins)

        outs = jax.eval_shape(f, *flat)
    except Exception:
        return  # metadata-only; executor will still compile with real shapes

    for slot, vals in outs.items():
        names = op.outputs.get(slot, [])
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            if val is None or not block._has_var_recursive(name):
                continue
            var = block._var_recursive(name)
            var.shape = tuple(-1 if d == _DYN else int(d) for d in val.shape)
            var.dtype = jnp.dtype(val.dtype).name if val.dtype != jnp.bfloat16 else "bfloat16"


def _infer_grad_shapes(block, op):
    """Grad of x has x's shape/dtype."""
    for slot, names in op.outputs.items():
        if not slot.endswith(GRAD_SUFFIX):
            continue
        src_slot = slot[: -len(GRAD_SUFFIX)]
        src_names = op.inputs.get(src_slot, [])
        for gname, sname in zip(names, src_names):
            if block._has_var_recursive(gname) and block._has_var_recursive(sname):
                gvar = block._var_recursive(gname)
                svar = block._var_recursive(sname)
                gvar.shape = tuple(svar.shape)
                gvar.dtype = svar.dtype
