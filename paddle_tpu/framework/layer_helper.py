"""LayerHelper — parity with python/paddle/fluid/layer_helper.py.

Bridges layer functions and the IR: creates parameters (with their init ops in
the default startup program), temp variables, and appends ops to the default
main program.
"""
from __future__ import annotations

from typing import Optional

from . import unique_name
from .core import dtype_is_floating
from .initializer import (
    ConstantInitializer,
    XavierInitializer,
    _global_bias_initializer,
    _global_weight_initializer,
)
from .param_attr import ParamAttr
from .program import default_main_program, default_startup_program


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_parameter(
        self,
        attr,
        shape,
        dtype,
        is_bias: bool = False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))
        init = attr.initializer or default_initializer
        if init is None:
            if is_bias:
                init = _global_bias_initializer()
            elif dtype_is_floating(dtype):
                init = _global_weight_initializer()
            else:
                init = ConstantInitializer(0.0)
        # main-program parameter
        param = self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs()
        )
        # startup-program twin + init op
        startup_block = self.startup_program.global_block()
        startup_param = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs()
        )
        init(startup_param, startup_block)
        return param

    def input_dtype(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name)
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return inputs[0].dtype

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        if any(d == -1 for d in size):
            raise ValueError(f"cannot infer bias shape from {input_var.shape}")
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type=act_type, inputs={"X": [input_var]}, outputs={"Out": [tmp]}, attrs=act
        )
        return tmp
