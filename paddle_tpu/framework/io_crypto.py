"""Encrypted model/checkpoint IO — capability parity with
paddle/fluid/framework/io/crypto/ (cipher.h Cipher/CipherFactory,
cipher_utils.h CipherUtils, aes_cipher.cc).

The reference links wolfSSL for AES-GCM. This build has no crypto
dependency, so the block cipher is a pure-python AES (FIPS-197 key schedule
+ rounds) in CTR mode with encrypt-then-MAC HMAC-SHA256 authentication —
same capability (confidential + tamper-evident checkpoint files), different
wire format (documented; reference files are key-private anyway, there is
no cross-reading use case). Checkpoint payloads are MBs, and CTR keystream
generation is the only per-byte python cost.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import struct
from typing import Dict

__all__ = ["Cipher", "AESCipher", "CipherFactory", "CipherUtils"]

# ---------------------------------------------------------------------------
# AES block cipher (FIPS-197), pure python
# ---------------------------------------------------------------------------

_SBOX = bytes.fromhex(
    "637c777bf26b6fc53001672bfed7ab76ca82c97dfa5947f0add4a2af9ca472c0"
    "b7fd9326363ff7cc34a5e5f171d8311504c723c31896059a071280e2eb27b275"
    "09832c1a1b6e5aa0523bd6b329e32f8453d100ed20fcb15b6acbbe394a4c58cf"
    "d0efaafb434d338545f9027f503c9fa851a3408f929d38f5bcb6da2110fff3d2"
    "cd0c13ec5f974417c4a77e3d645d197360814fdc222a908846eeb814de5e0bdb"
    "e0323a0a4906245cc2d3ac629195e479e7c8376d8dd54ea96c56f4ea657aae08"
    "ba78252e1ca6b4c6e8dd741f4bbd8b8a703eb5664803f60e613557b986c11d9e"
    "e1f8981169d98e949b1e87e9ce5528df8ca1890dbfe6426841992d0fb054bb16")
_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36,
         0x6C, 0xD8, 0xAB, 0x4D]


def _xtime(a):
    a <<= 1
    return (a ^ 0x1B) & 0xFF if a & 0x100 else a


_MUL2 = bytes(_xtime(i) for i in range(256))
_MUL3 = bytes(_MUL2[i] ^ i for i in range(256))


def _expand_key(key: bytes):
    nk = len(key) // 4
    nr = nk + 6
    words = [list(key[4 * i:4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (nr + 1)):
        t = list(words[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [_SBOX[b] for b in t]
            t[0] ^= _RCON[i // nk - 1]
        elif nk > 6 and i % nk == 4:
            t = [_SBOX[b] for b in t]
        words.append([a ^ b for a, b in zip(words[i - nk], t)])
    return [[b for word in words[4 * r:4 * r + 4] for b in word]
            for r in range(nr + 1)], nr


def _encrypt_block(block: bytes, round_keys, nr: int) -> bytes:
    s = [b ^ k for b, k in zip(block, round_keys[0])]
    for rnd in range(1, nr):
        s = [_SBOX[b] for b in s]
        # ShiftRows on column-major state: byte i sits at row i%4, col i//4
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        ns = []
        for c in range(4):
            a0, a1, a2, a3 = s[4 * c:4 * c + 4]
            ns += [
                _MUL2[a0] ^ _MUL3[a1] ^ a2 ^ a3,
                a0 ^ _MUL2[a1] ^ _MUL3[a2] ^ a3,
                a0 ^ a1 ^ _MUL2[a2] ^ _MUL3[a3],
                _MUL3[a0] ^ a1 ^ a2 ^ _MUL2[a3],
            ]
        s = [b ^ k for b, k in zip(ns, round_keys[rnd])]
    s = [_SBOX[b] for b in s]
    s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
    return bytes(b ^ k for b, k in zip(s, round_keys[nr]))


def _ctr_keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    round_keys, nr = _expand_key(key)
    out = bytearray()
    counter = 0
    while len(out) < n:
        block = nonce + struct.pack(">Q", counter)
        out += _encrypt_block(block, round_keys, nr)
        counter += 1
    return bytes(out[:n])


# ---------------------------------------------------------------------------
# Cipher API (cipher.h)
# ---------------------------------------------------------------------------

_MAGIC_V1 = b"PTPUAE1\0"   # legacy: one key for both CTR and HMAC
_MAGIC = b"PTPUAE2\0"      # v2: HKDF-style enc/mac subkey separation


def _subkeys(key: bytes, key_bytes: int):
    """Derive independent encryption/MAC subkeys (encrypt-then-MAC key
    separation): enc = HMAC(key, 'enc'), mac = HMAC(key, 'mac')."""
    enc = hmac_mod.new(key, b"enc", hashlib.sha256).digest()[:key_bytes]
    mac = hmac_mod.new(key, b"mac", hashlib.sha256).digest()
    return enc, mac


class Cipher:
    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def encrypt_to_file(self, plaintext: bytes, key: bytes,
                        filename: str) -> None:
        data = self.encrypt(plaintext, key)
        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        with open(filename, "wb") as f:
            f.write(data)

    def decrypt_from_file(self, key: bytes, filename: str) -> bytes:
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key)

    # CamelCase aliases matching cipher.h method names
    Encrypt = encrypt
    Decrypt = decrypt
    EncryptToFile = encrypt_to_file
    DecryptFromFile = decrypt_from_file


class AESCipher(Cipher):
    """AES-CTR + HMAC-SHA256 (encrypt-then-MAC). File layout:
    magic(8) | nonce(8) | ciphertext | hmac(32)."""

    def __init__(self, key_bits: int = 256):
        if key_bits not in (128, 192, 256):
            raise ValueError(f"bad AES key size {key_bits}")
        self.key_bytes = key_bits // 8

    def _norm_key(self, key: bytes) -> bytes:
        if isinstance(key, str):
            key = key.encode()
        if len(key) != self.key_bytes:
            key = hashlib.sha256(key).digest()[: self.key_bytes]
        return key

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        if isinstance(plaintext, str):
            plaintext = plaintext.encode()
        key = self._norm_key(key)
        enc_key, mac_key = _subkeys(key, self.key_bytes)
        nonce = os.urandom(8)
        stream = _ctr_keystream(enc_key, nonce, len(plaintext))
        ct = bytes(p ^ s for p, s in zip(plaintext, stream))
        mac = hmac_mod.new(mac_key, _MAGIC + nonce + ct,
                           hashlib.sha256).digest()
        return _MAGIC + nonce + ct + mac

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        key = self._norm_key(key)
        magic = ciphertext[:8]
        if len(ciphertext) < 48 or magic not in (_MAGIC, _MAGIC_V1):
            raise ValueError("not a paddle_tpu encrypted blob")
        if magic == _MAGIC_V1:       # legacy files: single shared key
            enc_key, mac_key = key, key
        else:
            enc_key, mac_key = _subkeys(key, self.key_bytes)
        nonce = ciphertext[8:16]
        ct, mac = ciphertext[16:-32], ciphertext[-32:]
        want = hmac_mod.new(mac_key, magic + nonce + ct,
                            hashlib.sha256).digest()
        if not hmac_mod.compare_digest(mac, want):
            raise ValueError("ciphertext authentication failed "
                             "(wrong key or tampered file)")
        stream = _ctr_keystream(enc_key, nonce, len(ct))
        return bytes(c ^ s for c, s in zip(ct, stream))


class CipherFactory:
    """cipher.h CipherFactory::CreateCipher — config file holds
    `cipher_name:AES_CTR_NoPadding` (reference uses AES_GCM_NoPadding(bits))
    + optional key size."""

    @staticmethod
    def create_cipher(config_file: str = None) -> Cipher:
        key_bits = 256
        if config_file and os.path.exists(config_file):
            cfg = CipherUtils.read_config(config_file)
            name = cfg.get("cipher_name", "")
            for bits in (128, 192, 256):
                if str(bits) in name or cfg.get("key_size") == str(bits):
                    key_bits = bits
        return AESCipher(key_bits)

    CreateCipher = create_cipher


class CipherUtils:
    """cipher_utils.h: key generation + config parsing."""

    @staticmethod
    def gen_key(length_bits: int) -> bytes:
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, filename: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        with open(filename, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(filename: str) -> bytes:
        with open(filename, "rb") as f:
            return f.read()

    @staticmethod
    def read_config(config_file: str) -> Dict[str, str]:
        out = {}
        for line in open(config_file):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            for sep in (":", "="):
                if sep in line:
                    k, v = line.split(sep, 1)
                    out[k.strip()] = v.strip()
                    break
        return out
