"""PartitionSpec annotations on the Program IR (GSPMD-style, ISSUE 12).

One spec format serves three layers (docs/sharding.md):

- **IR annotations**: ``shard_tensor(var, ("dp", None))`` attaches a
  JSON-serializable per-dim axis-name tuple to a ``Variable``; the desc
  round-trip (framework/serialization.py) and ``Program.clone`` preserve
  it, and the executor's gspmd mode already consumes ``var.sharding`` when
  building ``NamedSharding``s.
- **Propagation** (propagate.py): the fixpoint pass reads annotated specs
  and derives everything else, merging by *refinement* — ``None`` (a
  replicated dim) may be refined to a named axis; two different named
  axes on the same dim are a conflict.
- **Lowering**: ``to_partition_spec`` converts to
  ``jax.sharding.PartitionSpec`` for ``jax.jit`` + ``NamedSharding``.

A spec here is a tuple with one entry per tensor dim: ``None`` (dim
replicated), an axis name string, or a tuple of axis names (dim sharded
over several axes, majorest first — jax PartitionSpec semantics). Specs
shorter than the tensor rank are padded with ``None`` on the right, the
same convention jax uses.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "SpecConflict", "normalize_spec", "spec_to_json", "spec_from_json",
    "to_partition_spec", "spec_axes", "pad_spec", "merge_specs",
    "is_replicated", "shard_tensor", "annotate_program", "annotated_vars",
    "mesh_axes_of", "spec_str", "shard_divisor",
]


class SpecConflict(ValueError):
    """Two specs demand different named axes on the same dim."""


def _norm_entry(entry):
    if entry is None:
        return None
    if isinstance(entry, str):
        return entry
    if isinstance(entry, (tuple, list)):
        axes = tuple(str(a) for a in entry)
        if not axes:
            return None
        if len(axes) == 1:
            return axes[0]
        return axes
    raise TypeError(f"bad PartitionSpec entry {entry!r}")


def normalize_spec(spec) -> Tuple:
    """Canonical tuple form from a jax PartitionSpec, list, or tuple."""
    if spec is None:
        return ()
    # jax.sharding.PartitionSpec is itself a tuple subclass on modern jax;
    # duck-type by iterating either way
    if isinstance(spec, (str,)):
        return (spec,)
    return tuple(_norm_entry(e) for e in spec)


def spec_to_json(spec) -> List:
    """JSON-able form (tuples become lists)."""
    out = []
    for e in normalize_spec(spec):
        out.append(list(e) if isinstance(e, tuple) else e)
    return out


def spec_from_json(data) -> Tuple:
    if data is None:
        return ()
    return normalize_spec(data)


def to_partition_spec(spec):
    """Canonical tuple -> jax.sharding.PartitionSpec."""
    from jax.sharding import PartitionSpec as P

    return P(*normalize_spec(spec))


def spec_axes(spec) -> Tuple[str, ...]:
    """Every mesh axis named by the spec, in order of first appearance."""
    out: List[str] = []
    for e in normalize_spec(spec):
        if e is None:
            continue
        for a in (e if isinstance(e, tuple) else (e,)):
            if a not in out:
                out.append(a)
    return tuple(out)


def pad_spec(spec, rank: int) -> Tuple:
    """Right-pad with None to ``rank`` entries (jax convention)."""
    s = normalize_spec(spec)
    if len(s) > rank:
        raise ValueError(f"spec {s} has more entries than tensor rank {rank}")
    return s + (None,) * (rank - len(s))


def is_replicated(spec) -> bool:
    return all(e is None for e in normalize_spec(spec))


def spec_str(spec) -> str:
    """Compact human form: P(dp, None) style."""
    parts = []
    for e in normalize_spec(spec):
        if e is None:
            parts.append("None")
        elif isinstance(e, tuple):
            parts.append("(" + ",".join(e) + ")")
        else:
            parts.append(str(e))
    return "P(" + ", ".join(parts) + ")"


def shard_divisor(spec, dim: int, mesh_sizes: Dict[str, int]) -> int:
    """How many ways ``dim`` is split under ``spec`` on a mesh of
    ``mesh_sizes`` ({axis: size}); unknown axes count as size 1."""
    s = normalize_spec(spec)
    if dim >= len(s) or s[dim] is None:
        return 1
    axes = s[dim] if isinstance(s[dim], tuple) else (s[dim],)
    n = 1
    for a in axes:
        n *= int(mesh_sizes.get(a, 1))
    return n


def merge_specs(a, b, rank: Optional[int] = None) -> Tuple:
    """Refinement merge: per dim, ``None`` yields to a named axis; two
    different named entries raise :class:`SpecConflict`.  ``rank`` pads
    both sides before merging (required when they differ in length)."""
    a, b = normalize_spec(a), normalize_spec(b)
    if rank is None:
        rank = max(len(a), len(b))
    a, b = pad_spec(a, rank), pad_spec(b, rank)
    out = []
    for d, (ea, eb) in enumerate(zip(a, b)):
        if ea == eb or eb is None:
            out.append(ea)
        elif ea is None:
            out.append(eb)
        else:
            raise SpecConflict(
                f"dim {d}: {spec_str(a)} vs {spec_str(b)} "
                f"({ea!r} != {eb!r})")
    return tuple(out)


# ---------------------------------------------------------------------------
# IR annotation API
# ---------------------------------------------------------------------------

def shard_tensor(var, spec) -> None:
    """Annotate one IR :class:`Variable` with a PartitionSpec.

    The canonical tuple lands on ``var.sharding`` (the attribute the
    executor's gspmd mode already reads) and survives desc serialization
    and ``Program.clone``. Rank is validated against the declared shape
    when one exists."""
    s = normalize_spec(spec)
    shape = tuple(getattr(var, "shape", ()) or ())
    if shape and len(s) > len(shape):
        raise ValueError(
            f"PartitionSpec {spec_str(s)} has {len(s)} entries but var "
            f"{var.name!r} has rank {len(shape)}")
    var.sharding = pad_spec(s, len(shape)) if shape else s


def _find_var(program, name: str):
    for block in program.blocks:
        if name in block.vars:
            return block.vars[name]
    return None


def annotate_program(program, annotations: Dict[str, Any],
                     mesh_axes: Optional[Sequence[Tuple[str, int]]] = None,
                     data_axis: Optional[str] = None) -> None:
    """Attach PartitionSpecs to named vars of ``program`` and (optionally)
    stamp the target mesh into ``program._annotations['mesh']`` in the
    executor's gspmd MeshPlan schema — annotated programs then lower
    through ``jax.jit`` + ``NamedSharding`` with no further plumbing.
    """
    missing = []
    for name, spec in annotations.items():
        var = _find_var(program, name)
        if var is None:
            missing.append(name)
            continue
        shard_tensor(var, spec)
    if missing:
        raise ValueError(
            f"annotate_program: no var(s) named {sorted(missing)} in the "
            "program")
    # record the EXPLICIT seed set: propagation anchors to it even after
    # apply_sharding writes derived specs onto every var
    seen = set(program._annotations.get("sharding_annotated") or [])
    program._annotations["sharding_annotated"] = sorted(
        seen | set(annotations))
    if mesh_axes is not None:
        program._annotations["mesh"] = {
            "mode": "gspmd",
            "axes": [(str(a), int(s)) for a, s in mesh_axes],
            "data_axis": data_axis,
            "ring_axes": {},
        }


def annotated_vars(program) -> Dict[str, Tuple]:
    """{var name: canonical spec} over every annotated var of every
    block (vars defaulted by propagation — all-None specs included)."""
    out: Dict[str, Tuple] = {}
    for block in program.blocks:
        for name, var in block.vars.items():
            s = getattr(var, "sharding", None)
            if s is not None:
                out[name] = normalize_spec(s)
    return out


def mesh_axes_of(program) -> Optional[List[Tuple[str, int]]]:
    """The annotated mesh axes, if any ([('dp', 8), ...])."""
    mesh = program._annotations.get("mesh") if hasattr(
        program, "_annotations") else None
    if not mesh:
        return None
    axes = mesh.get("axes") or ()
    return [(str(a), int(s)) for a, s in axes] or None
