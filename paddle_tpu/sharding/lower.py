"""Lowering annotated programs through ``jax.jit`` + ``NamedSharding``.

The executor's gspmd mode (framework/executor.py `_CompiledBlock`) already
builds ``NamedSharding``s from ``var.sharding`` and a mesh annotation —
so lowering an annotated program is: run propagation, write every
propagated spec back onto the IR vars, stamp the mesh plan, and let
``Executor.run`` compile it like any other gspmd program. One mechanism,
no parallel lowering path.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

from . import propagate as propagate_mod
from . import spec as spec_mod

__all__ = ["apply_sharding", "named_shardings", "mesh_from_axes"]


def mesh_from_axes(mesh_axes: Sequence[Tuple[str, int]], devices=None):
    """Build a jax Mesh for ``[(axis, size), ...]`` (thin alias of
    parallel.mesh.build_mesh so callers need one import)."""
    from ..parallel.mesh import build_mesh

    return build_mesh(list(mesh_axes), devices)


def apply_sharding(program,
                   mesh_axes: Optional[Sequence[Tuple[str, int]]] = None,
                   data_axis: Optional[str] = None,
                   feed_specs: Optional[Dict[str, Any]] = None,
                   strict: bool = False):
    """Propagate and APPLY: every var of ``program`` gets its propagated
    spec as ``var.sharding`` and the program gets a gspmd mesh annotation
    — after this, ``Executor.run`` lowers it through ``jax.jit`` +
    ``NamedSharding`` on the annotated mesh.

    ``strict=True`` raises on propagation conflicts (the lint checker
    reports them with locations either way). Returns the
    :class:`~paddle_tpu.sharding.propagate.PropagationResult`.
    """
    if mesh_axes is None:
        mesh_axes = spec_mod.mesh_axes_of(program)
        if mesh_axes is None:
            raise ValueError(
                "apply_sharding: no mesh_axes given and the program has "
                "no mesh annotation (annotate_program(..., mesh_axes=))")
    result = propagate_mod.propagate_program(
        program, mesh_axes=mesh_axes, feed_specs=feed_specs)
    if strict and result.conflicts:
        raise spec_mod.SpecConflict(
            "sharding propagation conflicts:\n" +
            "\n".join(c.format() for c in result.conflicts))
    # remember the explicit seeds BEFORE writing every propagated spec
    # back, so re-propagation (lint, debugger) stays anchored to the
    # user's annotations rather than the derived fixpoint
    explicit = sorted(result.annotated)
    for block in program.blocks:
        for name, var in block.vars.items():
            s = result.specs.get(name)
            if s is not None:
                var.sharding = s
    ann = program._annotations
    ann["sharding_annotated"] = explicit
    mesh = dict(ann.get("mesh") or {})
    mesh.setdefault("mode", "gspmd")
    mesh["axes"] = [(str(a), int(s)) for a, s in mesh_axes]
    if data_axis is not None:
        mesh["data_axis"] = data_axis
    mesh.setdefault("data_axis", None)
    mesh.setdefault("ring_axes", {})
    ann["mesh"] = mesh
    program._bump_version()
    return result


def named_shardings(result, mesh, names: Optional[Sequence[str]] = None
                    ) -> Dict[str, Any]:
    """{var: NamedSharding} for (a subset of) a propagation result."""
    from jax.sharding import NamedSharding

    names = list(names) if names is not None else sorted(result.specs)
    return {n: NamedSharding(mesh, spec_mod.to_partition_spec(
        result.specs[n])) for n in names if n in result.specs}
