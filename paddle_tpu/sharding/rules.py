"""Per-op sharding-propagation rules (ISSUE 12; docs/sharding.md).

Each rule is ``fn(ctx: propagate.RuleCtx, op)`` and registers on the op
registry via ``framework.registry.set_sharding_rule`` — the exact sibling
of the declared ``infer_shape`` specs, so rule coverage is auditable the
same way (``PropagationResult.coverage`` / the lint checker's report).

Rules derive specs in BOTH directions (the driver alternates forward and
backward sweeps) and use three verbs only:

- ``ctx.propose(name, spec)`` — refine a var's spec (None dims yield to
  named axes; contradictions become recorded conflicts, never silent);
- ``ctx.tie(a, b)`` — two vars share a layout (identity ops, optimizer
  in-place updates, grad/primal pairs);
- ``ctx.reshard(name, to_spec, kind, reason)`` — the op needs an operand
  laid out differently: record the implied collective + ring-model cost
  and continue with the post-reshard spec.

Families covered first (the ISSUE 12 floor): elementwise (+broadcast
bias adds), matmul (``mul``/``matmul``/``matmul_v2`` — row/column
parallel and the Megatron partial-sum pair), reductions, transpose,
reshape (conservative), embedding lookups, softmax CE, optimizer ops,
and the shape-preserving ``c_*`` collectives. Everything else takes the
replicate fallback and shows up in the coverage report.
"""
from __future__ import annotations

from typing import List

from .spec import is_replicated

_REGISTERED = False


def _set(op_type: str, fn) -> None:
    from ..framework import registry

    if op_type in registry._OPS:
        registry.set_sharding_rule(op_type, fn)


# ---------------------------------------------------------------------------
# family rule builders
# ---------------------------------------------------------------------------

def _first(op, slot):
    names = (op.inputs or {}).get(slot) or (op.outputs or {}).get(slot) or []
    return names[0] if names and names[0] != "@EMPTY@" else None


def identity_rule(in_slot: str = "X", out_slot: str = "Out"):
    """Every ``out_slot`` output shares the matching ``in_slot`` input's
    layout (unary math, casts, activations)."""

    def rule(ctx, op):
        ins = (op.inputs or {}).get(in_slot, [])
        outs = (op.outputs or {}).get(out_slot, [])
        for a, b in zip(ins, outs):
            if a and b and a != "@EMPTY@" and b != "@EMPTY@":
                ctx.tie(a, b)

    return rule


def elementwise_rule(ctx, op):
    """Out shards like X; the (possibly broadcast) Y operand aligns at
    attr ``axis`` and inherits the overlapping entries where its dims
    match X's (a size-1 broadcast dim stays replicated)."""
    x, y = _first(op, "X"), _first(op, "Y")
    out = _first(op, "Out")
    if not (x and out):
        return
    ctx.tie(x, out)
    if not y:
        return
    rx, ry = ctx.rank(x), ctx.rank(y)
    if rx is None or ry is None:
        return
    if rx == ry:
        sx, sy = ctx.shape(x), ctx.shape(y)
        if sx == sy:
            ctx.tie(x, y)
        return
    if ry > rx:
        return
    axis = int(ctx.attr("axis", -1))
    if axis < 0:
        axis = rx - ry
    src = ctx.spec(x) or ctx.spec(out)
    if src is None:
        return
    sy = ctx.shape(y)
    sx = ctx.shape(x)
    prop = []
    for d in range(ry):
        xd = axis + d
        if sy[d] == 1 or (sx and 0 <= xd < len(sx)
                          and sx[xd] not in (-1, sy[d])):
            prop.append(None)
        else:
            prop.append(src[xd])
    ctx.propose(y, tuple(prop))


def matmul_rule(ctx, op):
    """``mul`` (x_num_col_dims/y_num_col_dims flattening) and jax-style
    matmul: output rows shard like X's row dims, output cols like Y's col
    dims. A sharded contracting dim on both sides (matching axes) is the
    Megatron partial-sum pair -> implied psum on the output edge; sharded
    on one side only -> implied gather of that operand."""
    x, y, out = _first(op, "X"), _first(op, "Y"), _first(op, "Out")
    if not (x and y and out):
        return
    rx, ry, ro = ctx.rank(x), ctx.rank(y), ctx.rank(out)
    if None in (rx, ry, ro):
        return
    if op.type == "mul":
        k = int(ctx.attr("x_num_col_dims", 1))
        m = int(ctx.attr("y_num_col_dims", 1))
    else:
        k, m = rx - 1, 1
        if bool(ctx.attr("transpose_Y", False) or
                ctx.attr("trans_y", False)):
            # Y [N, K]: cols come from dim 0 — handle via reversed view
            m = ry - 1
    sx = ctx.spec(x)
    sy = ctx.spec(y)
    so = ctx.spec(out)

    x_contract = tuple(range(k, rx))
    y_contract = tuple(range(0, m))
    y_cols = tuple(range(m, ry))

    # contracting-dim analysis (forward only; needs both operand specs)
    if sx is not None and sy is not None:
        xc = [sx[d] for d in x_contract]
        yc = [sy[d] for d in y_contract]
        x_sharded = any(e is not None for e in xc)
        y_sharded = any(e is not None for e in yc)
        if x_sharded and y_sharded:
            if xc == yc:
                # Megatron pair: local partial matmul + implied psum of
                # the output
                axes = []
                for e in xc:
                    if e is None:
                        continue
                    axes.extend(e if isinstance(e, tuple) else (e,))
                ctx.partial_sum(out, axes,
                                "contracting dim sharded on both "
                                "operands (row-parallel matmul)")
            else:
                sx = ctx.reshard(
                    x, tuple(sx[d] if d < k else None for d in range(rx)),
                    "gather", "contracting-dim layouts disagree")
        elif x_sharded:
            sx = ctx.reshard(
                x, tuple(sx[d] if d < k else None for d in range(rx)),
                "gather", "contracting dim of X sharded, Y replicated")
        elif y_sharded:
            sy = ctx.reshard(
                y, tuple(None if d < m else sy[d] for d in range(ry)),
                "gather", "contracting dim of Y sharded, X replicated")

    # forward: out rows from X rows, out cols from Y cols
    prop_out: List = [None] * ro
    known = False
    if sx is not None:
        for d in range(min(k, ro)):
            prop_out[d] = sx[d]
        known = True
    if sy is not None:
        for i, d in enumerate(y_cols):
            od = k + i
            if od < ro:
                prop_out[od] = sy[d]
        known = True
    if known:
        ctx.propose(out, tuple(prop_out))
    # backward: X rows from out rows, Y cols from out cols
    if so is not None:
        px: List = [None] * rx
        for d in range(min(k, ro)):
            px[d] = so[d]
        ctx.propose(x, tuple(px))
        py: List = [None] * ry
        for i, d in enumerate(y_cols):
            od = k + i
            if od < ro:
                py[d] = so[od]
        ctx.propose(y, tuple(py))


def reduce_rule(ctx, op):
    """reduce_* over attr dims: kept dims pass through; reducing a
    sharded dim implies a psum reshard of the (replicated) output."""
    x, out = _first(op, "X"), _first(op, "Out")
    if not (x and out):
        return
    rx, ro = ctx.rank(x), ctx.rank(out)
    if rx is None or ro is None:
        return
    dims = ctx.attr("dim", [])
    reduce_all = bool(ctx.attr("reduce_all", False)) or not dims
    keep = bool(ctx.attr("keep_dim", False))
    if isinstance(dims, int):
        dims = [dims]
    dims = sorted(d % rx for d in dims) if not reduce_all \
        else list(range(rx))
    sx = ctx.spec(x)
    if sx is not None:
        red_axes = []
        for d in dims:
            e = sx[d]
            if e is not None:
                red_axes.extend(e if isinstance(e, tuple) else (e,))
        if red_axes:
            ctx.partial_sum(out, red_axes,
                            "reduction over a sharded dim")
        prop = []
        for d in range(rx):
            if d in dims:
                if keep:
                    prop.append(None)
            else:
                prop.append(sx[d])
        if len(prop) == ro:
            ctx.propose(out, tuple(prop))
        elif ro in (0, 1):
            ctx.propose(out, (None,) * ro)
    so = ctx.spec(out)
    if so is not None and not reduce_all and len(so) == ro:
        # backward: kept dims flow back
        px: List = [None] * rx
        i = 0
        for d in range(rx):
            if d in dims:
                if keep:
                    i += 1
                continue
            if i < ro:
                px[d] = so[i]
            i += 1
        ctx.propose(x, tuple(px))


def transpose_rule(ctx, op):
    x, out = _first(op, "X"), _first(op, "Out")
    if not (x and out):
        return
    perm = ctx.attr("axis", None) or ctx.attr("perm", None)
    rx = ctx.rank(x)
    if perm is None or rx is None:
        return
    perm = [int(p) % rx for p in perm]
    sx, so = ctx.spec(x), ctx.spec(out)
    if sx is not None:
        ctx.propose(out, tuple(sx[p] for p in perm))
    if so is not None and len(so) == len(perm):
        inv = [0] * len(perm)
        for i, p in enumerate(perm):
            inv[p] = i
        ctx.propose(x, tuple(so[inv[d]] for d in range(rx)))


def reshape_rule(ctx, op):
    """Conservative: replicated stays replicated; a sharded input whose
    leading dims survive unchanged carries those entries; anything else
    reshards to replicated (GSPMD's reshape rules are richer — this is
    the honest floor)."""
    x, out = _first(op, "X"), _first(op, "Out")
    if not (x and out):
        return
    sx_shape, so_shape = ctx.shape(x), ctx.shape(out)
    sx, so = ctx.spec(x), ctx.spec(out)
    ro = ctx.rank(out)
    rx = ctx.rank(x)

    def carry(src_spec, src_shape, dst_shape, dst_rank):
        if src_spec is None:
            return None
        if is_replicated(src_spec):
            return (None,) * dst_rank
        prop: List = [None] * dst_rank
        for d, e in enumerate(src_spec):
            if e is None:
                continue
            if d < dst_rank and src_shape and dst_shape \
                    and d < len(src_shape) and d < len(dst_shape) \
                    and src_shape[d] == dst_shape[d] \
                    and src_shape[:d] == dst_shape[:d]:
                prop[d] = e
            else:
                return "reshard"
        return tuple(prop)

    fwd = carry(sx, sx_shape, so_shape, ro or 0)
    if fwd == "reshard":
        sx = ctx.reshard(x, (None,) * (rx or 0), "replicate",
                         "reshape folds a sharded dim")
        ctx.propose(out, (None,) * (ro or 0))
    elif fwd is not None:
        ctx.propose(out, fwd)
    bwd = carry(so, so_shape, sx_shape, rx or 0)
    if bwd not in (None, "reshard"):
        ctx.propose(x, bwd)


def embedding_rule(ctx, op):
    """lookup_table(_v2): Out rows shard like Ids; Out's feature dim
    shards like W's. A vocab-sharded table implies a psum-style combine
    of the one-hot partial lookups."""
    w = _first(op, "W")
    ids = _first(op, "Ids")
    out = _first(op, "Out")
    if not (w and ids and out):
        return
    ri, ro, rw = ctx.rank(ids), ctx.rank(out), ctx.rank(w)
    if None in (ri, ro, rw):
        return
    si, sw, so = ctx.spec(ids), ctx.spec(w), ctx.spec(out)
    if sw is not None and sw[0] is not None:
        e = sw[0]
        ctx.partial_sum(out, e if isinstance(e, tuple) else (e,),
                        "vocab-sharded embedding table (partial "
                        "lookups)")
        sw = tuple([None] + list(sw[1:]))
    ids_shape = ctx.shape(ids)
    # classic lookup_table ids are [..., 1]; v2 drops the trailing 1
    squeeze = bool(ids_shape) and ids_shape[-1] == 1 and ro == ri
    row_rank = (ri - 1) if squeeze else ri
    prop: List = [None] * ro
    known = False
    if si is not None:
        for d in range(min(row_rank, ro)):
            prop[d] = si[d]
        known = True
    if sw is not None and ro >= 1:
        prop[ro - 1] = sw[rw - 1]
        known = True
    if known:
        ctx.propose(out, tuple(prop))
    if so is not None:
        pi: List = [None] * ri
        for d in range(min(row_rank, ro)):
            pi[d] = so[d]
        ctx.propose(ids, tuple(pi))


def softmax_ce_rule(ctx, op):
    """softmax_with_cross_entropy: the class dim must be whole (the
    conservative rule; a sharded-LSE rule would be the tp-native CE).
    Loss/Softmax rows shard like Logits rows; Label ties to the rows."""
    logits = _first(op, "Logits")
    label = _first(op, "Label")
    loss = _first(op, "Loss")
    soft = _first(op, "Softmax")
    if not (logits and loss):
        return
    rl = ctx.rank(logits)
    if rl is None:
        return
    sl = ctx.spec(logits)
    if sl is not None and sl[rl - 1] is not None:
        sl = ctx.reshard(
            logits, tuple(list(sl[:-1]) + [None]), "gather",
            "softmax CE needs the class dim unsharded (conservative "
            "rule)")
    rows = None if sl is None else tuple(sl[:-1])
    for tgt in (loss, soft, label):
        if not tgt:
            continue
        rt = ctx.rank(tgt)
        if rt is None:
            continue
        if rows is not None:
            prop = list(rows[:rt]) + [None] * max(0, rt - len(rows))
            if rt == len(rows) + 1:
                prop = list(rows) + [None]
            ctx.propose(tgt, tuple(prop[:rt]))
    # backward: logits rows from loss rows
    if loss:
        slo = ctx.spec(loss)
        if slo is not None:
            prop = list(slo[:rl - 1]) + [None] * max(0, rl - len(slo))
            prop = (prop + [None])[:rl]
            prop[rl - 1] = None
            ctx.propose(logits, tuple(prop))


def optimizer_rule(ctx, op):
    """In-place optimizer ops: every ``<Slot>Out`` output ties to its
    ``<Slot>`` input; Grad and moments tie to Param (they share the
    param's layout — exactly how the engine lays sharded state out)."""
    ins = op.inputs or {}
    outs = op.outputs or {}
    for slot, names in outs.items():
        base = slot[:-3] if slot.endswith("Out") else None
        if base and base in ins:
            for a, b in zip(ins[base], names):
                if a and b and a != "@EMPTY@" and b != "@EMPTY@":
                    ctx.tie(a, b)
    param = _first(op, "Param")
    if not param:
        return
    for slot in ("Grad", "Moment", "Moment1", "Moment2", "Velocity",
                 "MeanSquare", "MeanGrad"):
        other = _first(op, slot)
        if other and ctx.rank(other) == ctx.rank(param):
            ctx.tie(param, other)


def replicated_out_rule(ctx, op):
    """Ops whose outputs are born replicated (fill_constant & friends)."""
    for names in (op.outputs or {}).values():
        for n in names:
            r = ctx.rank(n)
            if r is not None:
                ctx.propose(n, (None,) * r)


def concat_rule(ctx, op):
    """concat: non-concat dims pass through from the first input; a
    sharded concat axis reshards to replicated."""
    ins = [n for n in (op.inputs or {}).get("X", []) if n != "@EMPTY@"]
    out = _first(op, "Out")
    if not (ins and out):
        return
    ro = ctx.rank(out)
    if ro is None:
        return
    axis = int(ctx.attr("axis", 0)) % max(ro, 1)
    prop: List = [None] * ro
    known = False
    for n in ins:
        s = ctx.spec(n)
        if s is None or len(s) != ro:
            continue
        known = True
        if s[axis] is not None:
            ctx.reshard(n, tuple(None if d == axis else s[d]
                                 for d in range(ro)),
                        "gather", "concat over a sharded dim")
            s = tuple(None if d == axis else s[d] for d in range(ro))
        for d in range(ro):
            if prop[d] is None:
                prop[d] = s[d]
    if known:
        prop[axis] = None
        ctx.propose(out, tuple(prop))
    so = ctx.spec(out)
    if so is not None:
        back = tuple(None if d == axis else so[d] for d in range(ro))
        for n in ins:
            if ctx.rank(n) == ro:
                ctx.propose(n, back)


def slice_rule(ctx, op):
    """slice: untouched dims pass through; slicing a sharded dim
    reshards it whole first."""
    x, out = _first(op, "Input") or _first(op, "X"), _first(op, "Out")
    if not (x and out):
        return
    rx, ro = ctx.rank(x), ctx.rank(out)
    if rx is None or ro is None or rx != ro:
        return
    axes = [int(a) % rx for a in (ctx.attr("axes", []) or [])]
    sx = ctx.spec(x)
    if sx is not None:
        if any(sx[d] is not None for d in axes):
            sx = ctx.reshard(
                x, tuple(None if d in axes else sx[d] for d in range(rx)),
                "gather", "slice over a sharded dim")
        ctx.propose(out, tuple(None if d in axes else sx[d]
                               for d in range(rx)))
    so = ctx.spec(out)
    if so is not None:
        ctx.propose(x, tuple(None if d in axes else so[d]
                             for d in range(rx)))


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

_ELEMENTWISE = ("elementwise_add", "elementwise_sub", "elementwise_mul",
                "elementwise_div", "elementwise_pow", "elementwise_max",
                "elementwise_min", "elementwise_mod",
                "elementwise_floordiv")

_IDENTITY = ("relu", "relu6", "gelu", "tanh", "sigmoid", "softplus",
             "softsign", "exp", "log", "sqrt", "rsqrt", "square", "abs",
             "ceil", "floor", "round", "reciprocal", "scale", "cast",
             "clip", "leaky_relu", "elu", "hard_sigmoid", "hard_swish",
             "swish", "stanh", "brelu", "soft_relu", "pow", "sign",
             "logsigmoid", "erf", "layer_norm", "softmax", "dropout",
             "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
             "c_allreduce_prod", "c_allreduce_avg", "c_broadcast",
             "c_identity", "c_sync_calc_stream", "c_sync_comm_stream",
             "assign", "share_data", "memcpy")

_REDUCE = ("reduce_mean", "reduce_sum", "reduce_max", "reduce_min",
           "reduce_prod", "reduce_any", "reduce_all", "mean")

_OPTIMIZER = ("sgd", "momentum", "adam", "adamw", "adamax", "adagrad",
              "rmsprop", "lamb", "lars_momentum", "decayed_adagrad",
              "ftrl", "dpsgd", "fused_sgd", "fused_momentum",
              "fused_adam", "fused_adamw")

_REPLICATED_OUT = ("fill_constant", "gaussian_random", "uniform_random",
                   "truncated_gaussian_random", "range", "shape",
                   "fill_zeros_like", "fill_any_like", "one_hot",
                   "one_hot_v2")


def ensure_registered() -> None:
    """Register every built-in rule once (idempotent; skips op types the
    registry doesn't know so optional families never hard-fail)."""
    global _REGISTERED
    if _REGISTERED:
        return
    from .. import ops  # noqa: F401  (op registrations, idempotent)
    from ..framework import registry

    if not registry._OPS:  # pragma: no cover - registry not populated yet
        return
    _REGISTERED = True

    for t in _IDENTITY:
        _set(t, identity_rule())
    for t in _ELEMENTWISE:
        _set(t, elementwise_rule)
    for t in _REDUCE:
        _set(t, reduce_rule)
    for t in _OPTIMIZER:
        _set(t, optimizer_rule)
    for t in _REPLICATED_OUT:
        _set(t, replicated_out_rule)
    for t in ("mul", "matmul", "matmul_v2"):
        _set(t, matmul_rule)
    for t in ("transpose", "transpose2"):
        _set(t, transpose_rule)
    for t in ("reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
              "unsqueeze2", "flatten", "flatten2",
              "flatten_contiguous_range"):
        _set(t, reshape_rule)
    for t in ("lookup_table", "lookup_table_v2"):
        _set(t, embedding_rule)
    _set("softmax_with_cross_entropy", softmax_ce_rule)
    _set("concat", concat_rule)
    _set("slice", slice_rule)
