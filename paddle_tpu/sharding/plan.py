"""Pytree-level sharding plans for the pure-JAX engine (ISSUE 12).

The Program-IR side (propagate.py) derives specs op-by-op; the engine
side (``parallelize.make_train_step(sharding=...)``) holds its state as
a param pytree, so the propagation twin here is **aval-suffix
inheritance**: the user annotates only the weight leaves (embedding +
attention/mlp matrices — the acceptance floor), and every unannotated
leaf inherits the trailing-dim entries of the annotated leaf whose shape
suffix it matches (a bias ``[..., F]`` inherits its weight's ``F``
entry; an ambiguous or unmatched leaf replicates). Optimizer moments
mirror the param specs leaf-for-leaf — exactly how fsdp's HBM saving
falls out.

Presets (``resolve_plan("dp" | "fsdp" | "tp")``) annotate the flagship
GPT pytree; arbitrary annotation dicts compose the same way.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from .spec import normalize_spec, pad_spec, spec_axes, spec_str

__all__ = ["ShardingPlan", "complete_pytree_specs", "gpt_annotations",
           "make_gpt_plan", "named_sharding_tree", "resolve_plan",
           "PRESETS"]

PRESETS = ("dp", "fsdp", "tp", "dp+tp")


def _path_str(path) -> str:
    parts = []
    for k in path:
        key = getattr(k, "key", None)
        if key is None:
            key = getattr(k, "idx", None)
        parts.append(str(key) if key is not None else str(k))
    return "/".join(parts)


@dataclasses.dataclass
class ShardingPlan:
    """A complete engine-side sharding: specs for every param leaf (as a
    pytree of jax PartitionSpecs), the data spec, and the derivation
    notes (leaf path -> "annotated" | "inherited:<source>" |
    "replicated")."""

    mode: str
    axes: Tuple[Tuple[str, int], ...]
    param_specs: Any
    data_spec: Any
    annotations: Dict[str, Any]
    derived: Dict[str, str]

    @property
    def mesh_sizes(self) -> Dict[str, int]:
        return {a: int(s) for a, s in self.axes}

    def params_replicated_over(self, axis: str) -> bool:
        """True when NO param leaf shards over ``axis`` (the comm_opt
        grad-reduction paths require dp-replicated params)."""
        import jax

        from jax.sharding import PartitionSpec as P

        for leaf in jax.tree_util.tree_leaves(
                self.param_specs, is_leaf=lambda x: isinstance(x, P)):
            if axis in spec_axes(tuple(leaf)):
                return False
        return True

    def report(self) -> str:
        lines = [f"sharding plan [{self.mode}] over mesh "
                 f"{dict(self.axes)}:"]
        for path in sorted(self.derived):
            lines.append(f"  {path}: {self.derived[path]}")
        return "\n".join(lines)


def complete_pytree_specs(avals, annotations: Dict[str, Any],
                          mesh_sizes: Dict[str, int]):
    """Derive a full spec pytree from annotations on a subset of leaves.

    ``avals`` is any pytree of arrays/ShapeDtypeStructs providing leaf
    shapes. Returns ``(specs_pytree, derived_notes)`` where the pytree
    holds jax PartitionSpecs. Inheritance: an unannotated leaf takes the
    trailing-dim spec entries of the annotated leaf whose shape suffix
    matches it longest; candidates that tie with different entries (or
    entries whose axes don't divide the dim) fall back to replicated.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    flat, treedef = jax.tree_util.tree_flatten_with_path(avals)
    shapes = {_path_str(p): tuple(x.shape) for p, x in flat}
    ann = {k: normalize_spec(v) for k, v in annotations.items()}
    unknown = sorted(set(ann) - set(shapes))
    if unknown:
        raise ValueError(
            f"sharding annotations name unknown leaves {unknown}; known: "
            f"{sorted(shapes)[:12]}...")

    def divides(entry, dim) -> bool:
        if entry is None:
            return True
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= int(mesh_sizes.get(a, 1))
        return dim % n == 0

    specs: Dict[str, Tuple] = {}
    derived: Dict[str, str] = {}
    for path, shape in shapes.items():
        if path in ann:
            s = pad_spec(ann[path], len(shape))
            for d, e in enumerate(s):
                if not divides(e, shape[d]):
                    raise ValueError(
                        f"annotation {spec_str(s)} on {path!r}: dim {d} "
                        f"({shape[d]}) not divisible by mesh axes {e!r}")
            specs[path] = s
            derived[path] = "annotated"
            continue
        # suffix inheritance from the best-matching annotated leaf
        best_t, best = 0, []
        for src, sspec in ann.items():
            sshape = shapes[src]
            sspec = pad_spec(sspec, len(sshape))
            t = 0
            while (t < len(shape) and t < len(sshape)
                   and shape[-1 - t] == sshape[-1 - t]):
                t += 1
            t = min(t, len(shape))
            if t == 0:
                continue
            inherited = tuple(sspec[len(sshape) - t:])
            if not all(divides(e, d) for e, d in
                       zip(inherited, shape[len(shape) - t:])):
                continue
            if t > best_t:
                best_t, best = t, [(src, inherited)]
            elif t == best_t:
                best.append((src, inherited))
        entries = {inh for _, inh in best}
        if best_t > 0 and len(entries) == 1:
            inherited = best[0][1]
            specs[path] = (None,) * (len(shape) - best_t) + inherited
            derived[path] = f"inherited:{best[0][0]}"
            if all(e is None for e in specs[path]):
                derived[path] = "replicated"
        else:
            specs[path] = (None,) * len(shape)
            derived[path] = ("replicated(ambiguous:"
                             + ",".join(sorted(s for s, _ in best)) + ")"
                             if best_t > 0 else "replicated")
    leaves = [P(*specs[_path_str(p)]) for p, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves), derived


def named_sharding_tree(specs, mesh):
    """PartitionSpec pytree -> NamedSharding pytree on ``mesh`` (the
    form ``jax.jit``'s in/out_shardings and ``jax.device_put`` take).
    Shared by the engine-side lowerings — training
    (`parallelize.make_train_step(sharding=...)`) and the serving
    engine's tensor-parallel mode (`serving/engine.py _init_tp`)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# GPT presets — the acceptance annotation set: embedding + attention/mlp
# weight leaves ONLY; everything else (biases, layernorms, moments, data)
# derives.
# ---------------------------------------------------------------------------

def gpt_annotations(mode: str, dp_axis: str = "dp",
                    tp_axis: str = "tp") -> Dict[str, Any]:
    if mode == "dp":
        # pure data parallel: weights explicitly replicated
        return {"wte": (), "lm_head": (),
                "blocks/w_qkv": (), "blocks/w_proj": (),
                "blocks/w_fc": (), "blocks/w_out": ()}
    if mode == "fsdp":
        # parameters sharded over the dp axis (one big dim per leaf);
        # GSPMD all-gathers for compute, reduce-scatters the grads
        return {
            "wte": (dp_axis, None),
            "lm_head": (None, dp_axis),
            "blocks/w_qkv": (None, dp_axis, None, None, None),
            "blocks/w_proj": (None, None, None, dp_axis),
            "blocks/w_fc": (None, None, dp_axis),
            "blocks/w_out": (None, dp_axis, None),
        }
    if mode in ("tp", "dp+tp"):
        # Megatron: column-parallel QKV/fc over heads/ffn, row-parallel
        # proj/out — the same split gpt.param_specs hand-writes, now
        # derived from six annotations
        return {
            "wte": (), "lm_head": (),
            "blocks/w_qkv": (None, None, None, tp_axis, None),
            "blocks/w_proj": (None, tp_axis, None, None),
            "blocks/w_fc": (None, None, tp_axis),
            "blocks/w_out": (None, tp_axis, None),
        }
    raise ValueError(f"unknown sharding preset {mode!r}; "
                     f"known: {PRESETS}")


def make_gpt_plan(cfg, pcfg, mode: str,
                  annotations: Optional[Dict[str, Any]] = None
                  ) -> ShardingPlan:
    """Plan for the flagship GPT pytree on ``pcfg``'s mesh axes.

    ``annotations`` overrides the preset annotation set (same leaf-path
    keys). Data stays batch-sharded over the dp axis in every mode."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..models import gpt as gpt_mod

    dp_ax, _pp_ax, tp_ax = pcfg.axis_names
    axes = tuple(zip(pcfg.axis_names, (pcfg.dp, pcfg.pp, pcfg.tp)))
    mesh_sizes = {a: int(s) for a, s in axes}
    if annotations is None:
        annotations = gpt_annotations(mode, dp_axis=dp_ax, tp_axis=tp_ax)
    avals = jax.eval_shape(lambda k: gpt_mod.init_params(k, cfg),
                           jax.ShapeDtypeStruct((2,), jnp.uint32))
    specs, derived = complete_pytree_specs(avals, annotations, mesh_sizes)
    return ShardingPlan(mode=mode, axes=axes, param_specs=specs,
                        data_spec=P(None, dp_ax, None),
                        annotations=dict(annotations), derived=derived)


def resolve_plan(sharding, cfg, pcfg) -> ShardingPlan:
    """Accept a preset name or a ready :class:`ShardingPlan`."""
    if isinstance(sharding, ShardingPlan):
        return sharding
    if isinstance(sharding, str):
        return make_gpt_plan(cfg, pcfg, sharding)
    if isinstance(sharding, dict):
        return make_gpt_plan(cfg, pcfg, "custom", annotations=sharding)
    raise TypeError(
        f"sharding= expects a preset name {PRESETS}, an annotation dict, "
        f"or a ShardingPlan; got {type(sharding).__name__}")
