"""GSPMD-style sharding propagation over the Program IR (ISSUE 12;
docs/sharding.md; GSPMD arXiv:2105.04663).

One sharding layer for dp / tp / fsdp and their compositions:

    from paddle_tpu import sharding

    # IR side: annotate a handful of vars, propagate, lower via the
    # executor's jax.jit + NamedSharding gspmd mode
    sharding.annotate_program(prog, {"x": ("dp", None)},
                              mesh_axes=[("dp", 8)], data_axis="dp")
    result = sharding.apply_sharding(prog)
    assert result.complete, result.report()

    # engine side: the same annotations drive the pure-JAX train step
    step = parallelize.make_train_step(cfg, pcfg, mesh, sharding="fsdp")
"""
from .spec import (SpecConflict, annotate_program, annotated_vars,  # noqa: F401
                   is_replicated, merge_specs, mesh_axes_of,
                   normalize_spec, pad_spec, shard_tensor, spec_axes,
                   spec_from_json, spec_str, spec_to_json,
                   to_partition_spec)
from .propagate import (Conflict, PropagationResult, Reshard,  # noqa: F401
                        RuleCtx, propagate_program)
from .lower import apply_sharding, mesh_from_axes, named_shardings  # noqa: F401
from .plan import (PRESETS, ShardingPlan, complete_pytree_specs,  # noqa: F401
                   gpt_annotations, make_gpt_plan, resolve_plan)
from . import rules as _rules

_rules.ensure_registered()

__all__ = [
    "SpecConflict", "annotate_program", "annotated_vars", "shard_tensor",
    "normalize_spec", "pad_spec", "merge_specs", "spec_axes", "spec_str",
    "spec_to_json", "spec_from_json", "to_partition_spec", "is_replicated",
    "mesh_axes_of",
    "Conflict", "PropagationResult", "Reshard", "RuleCtx",
    "propagate_program", "apply_sharding", "named_shardings",
    "mesh_from_axes",
    "PRESETS", "ShardingPlan", "complete_pytree_specs", "gpt_annotations",
    "make_gpt_plan", "resolve_plan",
]
