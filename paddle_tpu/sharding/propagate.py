"""GSPMD-style forward/backward fixpoint sharding propagation over the
Program IR (ISSUE 12; arXiv:2105.04663).

A handful of user annotations (``spec.annotate_program``) plus per-op
rules (rules.py, registered alongside the registry's ``infer_shape``
specs via ``framework.registry.set_sharding_rule``) suffice to derive a
PartitionSpec for EVERY var of a program:

- each rule derives/refines specs in both directions (outputs from
  inputs on the forward sweep, inputs from outputs on the backward
  sweep); the driver alternates sweeps until a fixpoint;
- merging is by *refinement* (spec.merge_specs): ``None`` dims yield to
  named axes; two different named axes on one dim is a **conflict** —
  recorded, never silently resolved;
- when an op needs an operand laid out differently than its producer
  made it (a matmul contracting over a sharded dim, a reduction over a
  sharded dim), the rule records an implied **reshard** on that edge
  with an estimated ring-model wire-byte cost (comm_opt.wire_bytes —
  the same accounting the runtime collectives use) and continues as if
  the operand had been resharded;
- ops with no registered rule fall back to conservative replication
  (sharded inputs get a ``replicate`` reshard record) and land in the
  **coverage report**, the to-do list for rule authors;
- grad ops need no rules at all: a generic tie pairs every
  ``<slot>@GRAD`` var with its primal (cotangents shard like their
  primals — the GSPMD invariant), which covers the default-vjp grad op
  family wholesale.

Every NEW reshard record increments
``paddle_resharding_bytes_total{edge}`` (edge = ``op_type/var``), gated
by tools/metrics_check.py.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

from ..observability import metrics as _obs_metrics
from . import spec as spec_mod
from .spec import (SpecConflict, merge_specs, normalize_spec, pad_spec,
                   spec_axes, spec_str)

__all__ = ["Reshard", "Conflict", "PropagationResult", "RuleCtx",
           "propagate_program", "GRAD_SUFFIX"]

GRAD_SUFFIX = "@GRAD"

# stand-in extent for -1 (batch) dims in reshard cost estimates — cost is
# an ordering signal, not an invoice; a nominal per-feed batch keeps the
# numbers finite and comparable
DYNAMIC_DIM_ESTIMATE = 32

_m_reshard_bytes = _obs_metrics.default_registry().counter(
    "paddle_resharding_bytes_total",
    "Estimated ring-model wire bytes of reshards implied by sharding "
    "propagation, by program edge (paddle_tpu/sharding/propagate.py)",
    ("edge",), max_series=256)

_DTYPE_BYTES = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
                "float16": 2, "bfloat16": 2, "int16": 2, "int8": 1,
                "uint8": 1, "bool": 1}


@dataclasses.dataclass
class Reshard:
    """One implied layout change on a (producer var -> consumer op) edge."""

    block_idx: int
    op_idx: int
    op_type: str
    var: str
    kind: str               # "gather" | "psum" | "replicate"
    from_spec: Tuple
    to_spec: Tuple
    bytes_est: int          # ring-model per-rank wire bytes (estimate)
    reason: str

    @property
    def edge(self) -> str:
        return f"{self.op_type}/{self.var}"

    def format(self) -> str:
        return (f"reshard[{self.kind}] {self.var!r} "
                f"{spec_str(self.from_spec)} -> {spec_str(self.to_spec)} "
                f"at block {self.block_idx} op {self.op_idx} "
                f"({self.op_type}), ~{self.bytes_est} wire B — "
                f"{self.reason}")


@dataclasses.dataclass
class Conflict:
    """Two propagation sources demanded different named axes on one dim."""

    block_idx: int
    op_idx: int
    op_type: str
    var: str
    existing: Tuple
    proposed: Tuple
    annotated: bool         # the losing proposal hit an EXPLICIT annotation
    detail: str

    def format(self) -> str:
        kind = "annotation" if self.annotated else "propagation"
        return (f"{kind} conflict on {self.var!r}: kept "
                f"{spec_str(self.existing)}, op {self.op_idx} "
                f"({self.op_type}, block {self.block_idx}) derived "
                f"{spec_str(self.proposed)} — {self.detail}")


class PropagationResult:
    def __init__(self, specs, annotated, conflicts, reshards, coverage,
                 defaulted, mesh_sizes, sweeps):
        self.specs: Dict[str, Tuple] = specs
        self.annotated: Dict[str, Tuple] = annotated
        self.conflicts: List[Conflict] = conflicts
        self.reshards: List[Reshard] = reshards
        # op_type -> "rule" | "grad_tie" | "fallback_replicate"
        self.coverage: Dict[str, str] = coverage
        self.defaulted: List[str] = defaulted
        self.mesh_sizes: Dict[str, int] = mesh_sizes
        self.sweeps = sweeps

    @property
    def complete(self) -> bool:
        """Every var got a spec with zero conflicts — the acceptance bar
        for an annotated program."""
        return not self.conflicts

    @property
    def total_reshard_bytes(self) -> int:
        return sum(r.bytes_est for r in self.reshards)

    def uncovered_op_types(self) -> List[str]:
        return sorted(t for t, how in self.coverage.items()
                      if how == "fallback_replicate")

    def report(self) -> str:
        lines = [
            f"sharding propagation: {len(self.specs)} var spec(s), "
            f"{len(self.annotated)} annotated, "
            f"{len(self.defaulted)} defaulted to replicated, "
            f"{len(self.conflicts)} conflict(s), "
            f"{len(self.reshards)} implied reshard(s) "
            f"(~{self.total_reshard_bytes} wire B), "
            f"{self.sweeps} sweep(s)"]
        for c in self.conflicts:
            lines.append("  " + c.format())
        for r in self.reshards:
            lines.append("  " + r.format())
        unc = self.uncovered_op_types()
        if unc:
            lines.append(f"  rule coverage gaps (replicate fallback): "
                         f"{', '.join(unc)}")
        return "\n".join(lines)


def _numel_est(shape) -> int:
    n = 1
    for d in (shape or ()):
        n *= DYNAMIC_DIM_ESTIMATE if int(d) < 0 else max(int(d), 1)
    return n


class RuleCtx:
    """What one sharding rule sees: the op, the spec environment, shapes,
    and the propose/tie/reshard verbs. Rules never mutate the program."""

    def __init__(self, engine, block, op_idx, op):
        self._e = engine
        self.block = block
        self.block_idx = block.idx
        self.op_idx = op_idx
        self.op = op
        self.mesh_sizes = engine.mesh_sizes

    # -- reads --------------------------------------------------------------
    def shape(self, name) -> Optional[Tuple[int, ...]]:
        return self._e.shape(name)

    def rank(self, name) -> Optional[int]:
        s = self.shape(name)
        return None if s is None else len(s)

    def spec(self, name) -> Optional[Tuple]:
        """Current spec of ``name`` padded to its rank; None = unknown."""
        s = self._e.env.get(name)
        if s is None:
            return None
        r = self.rank(name)
        return pad_spec(s, r) if r is not None else s

    def dtype_bytes(self, name) -> int:
        v = self._e.var(name)
        return _DTYPE_BYTES.get(str(getattr(v, "dtype", "float32")), 4)

    def attr(self, name, default=None):
        return self.op.attr(name, default)

    # -- writes -------------------------------------------------------------
    def propose(self, name, spec) -> None:
        self._e.propose(self, name, spec)

    def tie(self, a: str, b: str) -> None:
        """Constrain two vars to the same spec (both directions)."""
        sa, sb = self._e.env.get(a), self._e.env.get(b)
        if sa is not None:
            self._e.propose(self, b, sa)
        if sb is not None:
            self._e.propose(self, a, sb)

    def reshard(self, name, to_spec, kind: str, reason: str) -> Tuple:
        """Record an implied reshard of ``name`` at this op; returns the
        post-reshard spec the rule should continue with."""
        return self._e.reshard(self, name, to_spec, kind, reason)

    def partial_sum(self, name, axes, reason: str) -> None:
        """Record an implied cross-rank sum of ``name`` over mesh
        ``axes`` — the value (not the layout) is partial per rank, so
        from/to specs coincide; the wire cost is a psum of the full
        tensor over those axes (Megatron row-parallel matmuls,
        reductions over sharded dims)."""
        self._e.partial_sum(self, name, axes, reason)


class _Engine:
    def __init__(self, program, mesh_sizes, annotated, feed_specs):
        self.program = program
        self.mesh_sizes = dict(mesh_sizes)
        self.env: Dict[str, Tuple] = {}
        self.annotated: Dict[str, Tuple] = {}
        self.conflicts: List[Conflict] = []
        self.reshards: List[Reshard] = []
        self._reshard_seen: Set[Tuple] = set()
        self._conflict_seen: Set[Tuple] = set()
        self.coverage: Dict[str, str] = {}
        self.changed = False
        self._vars: Dict[str, Any] = {}
        for block in program.blocks:
            for name, var in block.vars.items():
                self._vars.setdefault(name, var)
        for name, s in annotated.items():
            r = self.rank_of(name)
            self.env[name] = pad_spec(s, r) if r is not None else \
                normalize_spec(s)
            self.annotated[name] = self.env[name]
        for name, s in (feed_specs or {}).items():
            if name in self._vars:
                r = self.rank_of(name)
                self.env[name] = pad_spec(s, r) if r is not None else \
                    normalize_spec(s)
                self.annotated.setdefault(name, self.env[name])

    def var(self, name):
        return self._vars.get(name)

    def shape(self, name):
        v = self._vars.get(name)
        if v is None:
            return None
        return tuple(getattr(v, "shape", ()) or ())

    def rank_of(self, name):
        s = self.shape(name)
        return None if s is None else len(s)

    def propose(self, ctx: RuleCtx, name, spec) -> None:
        if name not in self._vars:
            return
        r = self.rank_of(name)
        try:
            s = pad_spec(normalize_spec(spec), r) if r is not None \
                else normalize_spec(spec)
        except ValueError:
            return  # rank mismatch (broadcasting op proposed too wide)
        old = self.env.get(name)
        if old is None:
            self.env[name] = s
            self.changed = True
            return
        try:
            merged = merge_specs(old, s, rank=r)
        except SpecConflict as e:
            key = (ctx.block_idx, ctx.op_idx, name, old, s)
            if key not in self._conflict_seen:
                self._conflict_seen.add(key)
                self.conflicts.append(Conflict(
                    block_idx=ctx.block_idx, op_idx=ctx.op_idx,
                    op_type=ctx.op.type, var=name, existing=old,
                    proposed=s, annotated=name in self.annotated,
                    detail=str(e)))
            return
        if merged != old:
            if name in self.annotated and merged != self.annotated[name]:
                # refinement of an explicit annotation is a conflict too:
                # the user said replicated, propagation wants sharded
                key = (ctx.block_idx, ctx.op_idx, name, old, s, "ann")
                if key not in self._conflict_seen:
                    self._conflict_seen.add(key)
                    self.conflicts.append(Conflict(
                        block_idx=ctx.block_idx, op_idx=ctx.op_idx,
                        op_type=ctx.op.type, var=name,
                        existing=old, proposed=s, annotated=True,
                        detail="propagation refines an explicit "
                               "annotation"))
                return
            self.env[name] = merged
            self.changed = True

    def reshard(self, ctx: RuleCtx, name, to_spec, kind, reason) -> Tuple:
        r = self.rank_of(name)
        frm = self.env.get(name, ())
        frm = pad_spec(frm, r) if r is not None else normalize_spec(frm)
        to = pad_spec(normalize_spec(to_spec), r) if r is not None \
            else normalize_spec(to_spec)
        if frm == to:
            return to
        key = (ctx.block_idx, ctx.op_idx, name, frm, to, kind)
        if key in self._reshard_seen:
            return to
        self._reshard_seen.add(key)
        # ring-model cost: payload = the full tensor, participants = every
        # rank the union of both specs spans (comm_opt.wire_bytes — the
        # same model runtime collectives record)
        from ..parallel import comm_opt

        axes = set(spec_axes(frm)) | set(spec_axes(to))
        ranks = 1
        for a in axes:
            ranks *= int(self.mesh_sizes.get(a, 1))
        payload = _numel_est(self.shape(name)) * \
            _DTYPE_BYTES.get(str(getattr(self.var(name), "dtype",
                                         "float32")), 4)
        op_kind = "psum" if kind == "psum" else "all_gather"
        bytes_est = comm_opt.wire_bytes(op_kind, payload, max(ranks, 1)) \
            if ranks > 1 else 0
        rec = Reshard(block_idx=ctx.block_idx, op_idx=ctx.op_idx,
                      op_type=ctx.op.type, var=name, kind=kind,
                      from_spec=frm, to_spec=to, bytes_est=bytes_est,
                      reason=reason)
        self.reshards.append(rec)
        if bytes_est:
            _m_reshard_bytes.labels(rec.edge).inc(bytes_est)
        return to

    def partial_sum(self, ctx: RuleCtx, name, axes, reason) -> None:
        axes = tuple(a for a in axes if a)
        if not axes:
            return
        key = (ctx.block_idx, ctx.op_idx, name, axes, "psum")
        if key in self._reshard_seen:
            return
        self._reshard_seen.add(key)
        from ..parallel import comm_opt

        ranks = 1
        for a in axes:
            ranks *= int(self.mesh_sizes.get(a, 1))
        payload = _numel_est(self.shape(name)) * \
            _DTYPE_BYTES.get(str(getattr(self.var(name), "dtype",
                                         "float32")), 4)
        bytes_est = comm_opt.wire_bytes("psum", payload, max(ranks, 1)) \
            if ranks > 1 else 0
        r = self.rank_of(name)
        cur = self.env.get(name, ())
        cur = pad_spec(cur, r) if r is not None else normalize_spec(cur)
        rec = Reshard(block_idx=ctx.block_idx, op_idx=ctx.op_idx,
                      op_type=ctx.op.type, var=name, kind="psum",
                      from_spec=cur, to_spec=cur, bytes_est=bytes_est,
                      reason=f"{reason} (sum over {'/'.join(axes)})")
        self.reshards.append(rec)
        if bytes_est:
            _m_reshard_bytes.labels(rec.edge).inc(bytes_est)


def _grad_tie(ctx: RuleCtx, op) -> None:
    """Generic grad-op rule: every ``<slot>@GRAD`` var shards like its
    primal — cotangents inherit primal layouts (the GSPMD invariant the
    default-vjp grad ops satisfy by construction)."""
    io = [(op.inputs or {}), (op.outputs or {})]
    primal_names: Dict[str, List[str]] = {}
    for m in io:
        for slot, names in m.items():
            if not slot.endswith(GRAD_SUFFIX):
                primal_names.setdefault(slot, list(names))
    for m in io:
        for slot, names in m.items():
            if not slot.endswith(GRAD_SUFFIX):
                continue
            base = slot[: -len(GRAD_SUFFIX)]
            for gname, pname in zip(names, primal_names.get(base, [])):
                if gname and pname and gname != "@EMPTY@" \
                        and pname != "@EMPTY@":
                    ctx.tie(gname, pname)


def propagate_program(program, mesh_axes=None, annotations=None,
                      feed_specs=None,
                      max_sweeps: int = 32) -> PropagationResult:
    """Run the fixpoint pass over ``program``; returns a
    :class:`PropagationResult` (never mutates the program — apply the
    result with :func:`paddle_tpu.sharding.apply_sharding`).

    ``annotations`` overrides the seed set ({name: spec}); by default the
    explicit annotations recorded by ``annotate_program`` are used, or —
    for programs annotated by hand via ``shard_tensor`` — every var
    carrying a ``sharding`` attribute. ``feed_specs`` adds specs for feed
    vars (the batch-axis seed the engine entry points supply).
    """
    from ..framework import registry
    from . import rules as _rules  # registers built-in rules (idempotent)

    _rules.ensure_registered()

    if mesh_axes is None:
        mesh_axes = spec_mod.mesh_axes_of(program) or []
    mesh_sizes = {str(a): int(s) for a, s in mesh_axes}

    if annotations is None:
        explicit = program._annotations.get("sharding_annotated") \
            if hasattr(program, "_annotations") else None
        all_ann = spec_mod.annotated_vars(program)
        if explicit:
            annotations = {n: all_ann[n] for n in explicit if n in all_ann}
        else:
            annotations = all_ann

    eng = _Engine(program, mesh_sizes, annotations, feed_specs)

    # cache (op -> rule resolution) once
    def rule_for(op):
        fn = registry.get_sharding_rule(op.type)
        if fn is not None:
            eng.coverage.setdefault(op.type, "rule")
            return fn
        if op.type.endswith("_grad") or any(
                s.endswith(GRAD_SUFFIX) for s in list(op.inputs or {})
                + list(op.outputs or {})):
            eng.coverage.setdefault(op.type, "grad_tie")
            return _grad_tie
        eng.coverage.setdefault(op.type, "fallback_replicate")
        return _fallback_replicate

    ordered = []
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            ordered.append((block, i, op))

    sweeps = 0
    for sweep in range(max_sweeps):
        eng.changed = False
        seq = ordered if sweep % 2 == 0 else list(reversed(ordered))
        for block, i, op in seq:
            ctx = RuleCtx(eng, block, i, op)
            try:
                rule_for(op)(ctx, op)
            except Exception:
                # a crashing rule must not take propagation down; the var
                # simply stays for the replicate fallback
                eng.coverage[op.type] = "fallback_replicate"
        sweeps = sweep + 1
        if not eng.changed:
            break

    # conservative fallback: every still-unknown var is replicated
    defaulted = []
    specs: Dict[str, Tuple] = {}
    for name, var in eng._vars.items():
        s = eng.env.get(name)
        if s is None:
            r = eng.rank_of(name) or 0
            s = (None,) * r
            defaulted.append(name)
        specs[name] = s

    return PropagationResult(
        specs=specs, annotated=dict(eng.annotated),
        conflicts=eng.conflicts, reshards=eng.reshards,
        coverage=dict(eng.coverage), defaulted=sorted(defaulted),
        mesh_sizes=mesh_sizes, sweeps=sweeps)


def _fallback_replicate(ctx: RuleCtx, op) -> None:
    """No rule: outputs replicate; sharded inputs imply a replicate
    reshard (the conservative GSPMD fallback)."""
    for names in (op.inputs or {}).values():
        for n in names:
            s = ctx.spec(n)
            if s is not None and not spec_mod.is_replicated(s):
                ctx.reshard(n, (None,) * len(s), "replicate",
                            f"op {op.type!r} has no sharding rule")
    for names in (op.outputs or {}).values():
        for n in names:
            r = ctx.rank(n)
            if r is not None:
                ctx.propose(n, (None,) * r)
