from .fs import FS, HDFSClient, LocalFS  # noqa: F401
