from .fs import FS, HDFSClient, LocalFS  # noqa: F401
from .http_server import KVServer  # noqa: F401
from .fleet_barrier_util import check_all_trainers_ready  # noqa: F401
from .fleet_util import FleetUtil  # noqa: F401
