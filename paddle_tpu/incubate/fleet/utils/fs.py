"""Filesystem abstraction for fleet checkpoints — capability parity with
python/paddle/fluid/incubate/fleet/utils/hdfs.py (HDFSClient shelling to
`hadoop fs`), plus an explicit LocalFS with the same method surface so
checkpoint code is storage-agnostic (the reference reaches local files via
raw os/shutil calls scattered through fleet_util).

HDFSClient degrades gracefully: constructing it without a hadoop binary
raises only when a command actually runs, and every method goes through one
retrying runner like the reference's __run_hdfs_cmd.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["FS", "LocalFS", "HDFSClient"]


class FS:
    """Common surface: exist/dir/file checks, ls, upload/download (no-ops
    locally), delete, rename, mkdirs, touch, cat."""

    def is_exist(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def ls(self, path) -> List[str]:
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def rename(self, src, dst, overwrite=False):
        raise NotImplementedError

    def touch(self, path):
        raise NotImplementedError

    def cat(self, path) -> bytes:
        raise NotImplementedError

    def upload(self, local_path, remote_path, overwrite=False):
        raise NotImplementedError

    def download(self, remote_path, local_path, overwrite=False):
        raise NotImplementedError

    def put_bytes(self, path, payload: bytes):
        """Write ``payload`` to ``path`` on THIS filesystem (write a local
        tempfile, then upload) — storage-agnostic, unlike open(path,'wb')
        which only touches the local disk."""
        import tempfile

        fd, tmp = tempfile.mkstemp(prefix="fs_put_")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            self.upload(tmp, path, overwrite=True)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)


class LocalFS(FS):
    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def ls(self, path):
        return sorted(os.path.join(path, p) for p in os.listdir(path))

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst, overwrite=False):
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        os.replace(src, dst)

    def touch(self, path):
        self.mkdirs(os.path.dirname(path) or ".")
        open(path, "ab").close()

    def cat(self, path):
        with open(path, "rb") as f:
            return f.read()

    def upload(self, local_path, remote_path, overwrite=False):
        if local_path == remote_path:
            return
        if os.path.exists(remote_path) and not overwrite:
            raise FileExistsError(remote_path)
        self.mkdirs(os.path.dirname(remote_path) or ".")
        shutil.copy2(local_path, remote_path)

    def download(self, remote_path, local_path, overwrite=False):
        self.upload(remote_path, local_path, overwrite)


class HDFSClient(FS):
    """hdfs.py:45 HDFSClient — every call shells `hadoop fs -D... <cmd>`
    with bounded retries. ``hadoop_bin`` is overridable for testing (the
    reference hardcodes ``<hadoop_home>/bin/hadoop``)."""

    def __init__(self, hadoop_home: str, configs: Optional[Dict] = None,
                 retry_times: int = 5, retry_sleep_second: float = 3.0,
                 hadoop_bin: Optional[str] = None):
        self.pre_commands = [hadoop_bin
                             or os.path.join(hadoop_home, "bin", "hadoop"),
                             "fs"]
        for k, v in (configs or {}).items():
            self.pre_commands.append(f"-D{k}={v}")
        self.retry_times = retry_times
        self.retry_sleep_second = retry_sleep_second

    # ------------------------------------------------------------------
    def _run(self, args: List[str], retry_times: Optional[int] = None
             ) -> Tuple[int, str, str]:
        cmd = self.pre_commands + args
        retries = self.retry_times if retry_times is None else retry_times
        rc, out, err = 1, "", ""
        for attempt in range(retries + 1):
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True)
                rc, out, err = proc.returncode, proc.stdout, proc.stderr
            except FileNotFoundError as e:
                raise RuntimeError(
                    f"hadoop binary not found: {cmd[0]!r} — pass a valid "
                    f"hadoop_home/hadoop_bin to HDFSClient") from e
            if rc == 0:
                break
            if attempt < retries:
                time.sleep(self.retry_sleep_second)
        return rc, out, err

    # ------------------------------------------------------------------
    def is_exist(self, path):
        rc, _, _ = self._run(["-test", "-e", path], retry_times=1)
        return rc == 0

    def is_dir(self, path):
        rc, _, _ = self._run(["-test", "-d", path], retry_times=1)
        return rc == 0

    def is_file(self, path):
        rc, _, _ = self._run(["-test", "-f", path], retry_times=1)
        return rc == 0

    def ls(self, path):
        rc, out, err = self._run(["-ls", path])
        if rc != 0:
            raise RuntimeError(f"hdfs ls {path} failed: {err}")
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return files

    def mkdirs(self, path):
        rc, _, err = self._run(["-mkdir", "-p", path])
        if rc != 0:
            raise RuntimeError(f"hdfs mkdirs {path} failed: {err}")

    def delete(self, path):
        rc, _, err = self._run(["-rm", "-r", "-f", path])
        if rc != 0:
            raise RuntimeError(f"hdfs delete {path} failed: {err}")

    def rename(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        rc, _, err = self._run(["-mv", src, dst])
        if rc != 0:
            raise RuntimeError(f"hdfs rename {src} {dst} failed: {err}")

    def touch(self, path):
        rc, _, err = self._run(["-touchz", path])
        if rc != 0:
            raise RuntimeError(f"hdfs touch {path} failed: {err}")

    def cat(self, path):
        rc, out, err = self._run(["-cat", path], retry_times=1)
        if rc != 0:
            raise RuntimeError(f"hdfs cat {path} failed: {err}")
        return out.encode()

    def upload(self, local_path, remote_path, overwrite=False):
        if overwrite and self.is_exist(remote_path):
            self.delete(remote_path)
        rc, _, err = self._run(["-put", local_path, remote_path])
        if rc != 0:
            raise RuntimeError(
                f"hdfs upload {local_path} -> {remote_path} failed: {err}")

    def download(self, remote_path, local_path, overwrite=False):
        if overwrite and os.path.exists(local_path):
            LocalFS().delete(local_path)
        rc, _, err = self._run(["-get", remote_path, local_path])
        if rc != 0:
            raise RuntimeError(
                f"hdfs download {remote_path} -> {local_path} failed: {err}")
