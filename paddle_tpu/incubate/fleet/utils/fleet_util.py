"""FleetUtil — the operational subset of
incubate/fleet/utils/fleet_util.py:53 that carries over to the TPU build:
rank-0 logging, scope-var zeroing, global AUC/metrics from the streaming
stat buckets (the auc op's StatPos/StatNeg), dense-param pulls, inference
model export, and done-file bookkeeping for pass-style training. The
BoxPS/xbox cache-model paths stay out (BoxPS hardware).
"""
from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["FleetUtil"]

_logger = logging.getLogger("paddle_tpu.fleet")


class FleetUtil:
    def __init__(self, mode: str = "transpiler", fleet=None):
        self.mode = mode
        self._fleet = fleet

    # -- rank-0 logging ----------------------------------------------------
    def _rank(self) -> int:
        if self._fleet is not None:
            try:
                return self._fleet.worker_index()
            except Exception:
                pass
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def rank0_print(self, s: str) -> None:
        if self._rank() == 0:
            print(s, flush=True)

    def rank0_info(self, s: str) -> None:
        if self._rank() == 0:
            _logger.info(s)

    def rank0_error(self, s: str) -> None:
        if self._rank() == 0:
            _logger.error(s)

    # -- scope utilities ---------------------------------------------------
    def set_zero(self, var_name: str, scope=None, param_type="int64"):
        """fleet_util.py:121 — zero a stat var (AUC buckets between passes)."""
        import jax.numpy as jnp

        from ....framework.executor import global_scope

        scope = scope or global_scope()
        var = scope.find_var(var_name)
        if var is None:
            raise KeyError(var_name)
        arr = np.asarray(var)
        scope.set_var(var_name, jnp.zeros(arr.shape, arr.dtype))

    # -- global metrics ----------------------------------------------------
    @staticmethod
    def _auc_from_stats(stat_pos: np.ndarray, stat_neg: np.ndarray) -> float:
        """AUC from per-threshold counts (auc op bucket layout)."""
        stat_pos = np.asarray(stat_pos, np.float64).reshape(-1)
        stat_neg = np.asarray(stat_neg, np.float64).reshape(-1)
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(len(stat_pos) - 1, -1, -1):
            auc += stat_neg[i] * tot_pos + stat_pos[i] * stat_neg[i] / 2.0
            tot_pos += stat_pos[i]
            tot_neg += stat_neg[i]
        return auc / tot_pos / tot_neg if tot_pos and tot_neg else 0.0

    def get_global_auc(self, scope=None, stat_pos: str = "_auc_stat_pos",
                       stat_neg: str = "_auc_stat_neg") -> float:
        """fleet_util.py:186 — AUC over ALL trainers: sum the local stat
        buckets across workers (fleet allreduce when available, else the
        local buckets) and integrate."""
        from ....framework.executor import global_scope

        scope = scope or global_scope()
        pos = np.asarray(scope.find_var(stat_pos))
        neg = np.asarray(scope.find_var(stat_neg))
        if self._fleet is not None:
            try:
                pos = self._fleet.all_reduce(pos)
                neg = self._fleet.all_reduce(neg)
            except Exception:
                pass
        return self._auc_from_stats(pos, neg)

    def print_global_auc(self, scope=None, stat_pos: str = "_auc_stat_pos",
                         stat_neg: str = "_auc_stat_neg",
                         print_prefix: str = "") -> float:
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f"{print_prefix} global auc = {auc:.6f}")
        return auc

    def get_global_metrics(self, scope=None, stat_pos: str = "_auc_stat_pos",
                           stat_neg: str = "_auc_stat_neg") -> Dict[str, float]:
        """fleet_util.py:1268 subset: auc + base counts from the buckets."""
        from ....framework.executor import global_scope

        scope = scope or global_scope()
        pos = np.asarray(scope.find_var(stat_pos), dtype=np.float64)
        neg = np.asarray(scope.find_var(stat_neg), dtype=np.float64)
        n_pos, n_neg = float(pos.sum()), float(neg.sum())
        total = n_pos + n_neg
        return {
            "auc": self._auc_from_stats(pos, neg),
            "actual_ctr": n_pos / total if total else 0.0,
            "total_ins_num": total,
            "pos_ins_num": n_pos,
        }

    # -- params / model io -------------------------------------------------
    def pull_all_dense_params(self, scope, program, endpoints: List[str],
                              trainer_id: int = 0):
        """fleet_util.py:833 — refresh every trainable param in scope from
        the pservers (PS-mode eval path)."""
        import jax.numpy as jnp

        from ....distributed import PSClient

        client = PSClient.instance(trainer_id)
        for p in program.global_block().all_parameters():
            if not getattr(p, "trainable", True):
                continue
            val = client.pull(endpoints[0], p.name)
            scope.set_var(p.name, jnp.asarray(np.asarray(val)))

    def save_paddle_inference_model(self, executor, dirname,
                                    feeded_var_names, target_vars,
                                    main_program=None, scope=None):
        """fleet_util.py:876 — plain save_inference_model (the xbox base
        conversion is BoxPS-specific)."""
        from .... import io

        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program=main_program)

    # -- pass/done-file bookkeeping ---------------------------------------
    def write_model_donefile(self, output_path: str, day, pass_id,
                             xbox_base_key=None, fs=None,
                             donefile_name: str = "donefile.txt"):
        """fleet_util.py:362 — append a done record after a pass's model is
        persisted, so downstream consumers only read finished models."""
        from .fs import LocalFS

        fs = fs or LocalFS()
        if self._rank() != 0:
            return
        model_path = f"{output_path}/{day}/{pass_id}"
        record = "\t".join([str(day), str(pass_id),
                            str(xbox_base_key or int(time.time())),
                            model_path])
        done = os.path.join(output_path, donefile_name)
        existing = fs.cat(done).decode() if fs.is_file(done) else ""
        if model_path in existing:
            return
        if not fs.is_dir(output_path):
            fs.mkdirs(output_path)
        tmp = os.path.join(output_path, donefile_name + ".tmp")
        payload = (existing + record + "\n").encode()
        # write locally then upload through fs so HDFS backends receive the
        # payload (a local open() would leave the remote tmp empty and the
        # rename would wipe the done-record history)
        fs.put_bytes(tmp, payload)
        fs.rename(tmp, done, overwrite=True)

    def get_last_save_model(self, output_path: str, fs=None,
                            donefile_name: str = "donefile.txt"):
        """fleet_util.py:1158 — (day, pass_id, path) of the newest record,
        or (-1, -1, "") when none exists."""
        from .fs import LocalFS

        fs = fs or LocalFS()
        done = os.path.join(output_path, donefile_name)
        if not fs.is_file(done):
            return -1, -1, ""
        lines = [l for l in fs.cat(done).decode().splitlines() if l.strip()]
        if not lines:
            return -1, -1, ""
        day, pass_id, _key, path = lines[-1].split("\t")
        return int(day), int(pass_id), path

    def get_online_pass_interval(self, days: str, hours: str,
                                 split_interval, split_per_pass,
                                 is_data_hourly_placed: bool = False):
        """fleet_util.py:1207 — enumerate the file-split names in each
        online-training pass."""
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left_train_hour = int(hours.split(" ")[0]) if hours else 0
        del left_train_hour  # parity arg; file naming below is canonical
        online_pass_interval = []
        for i in range(pass_per_day):
            passes = []
            for j in range(split_per_pass):
                split_idx = i * split_per_pass + j
                h = split_idx * split_interval // 60
                m = split_idx * split_interval % 60
                if is_data_hourly_placed:
                    passes.append(f"{h:02d}")
                else:
                    passes.append(f"{h:02d}{m:02d}")
            online_pass_interval.append(passes)
        return online_pass_interval
