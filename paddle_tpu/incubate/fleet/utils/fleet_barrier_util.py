"""Trainer file-barrier — parity with
incubate/fleet/utils/fleet_barrier_util.py:21 check_all_trainers_ready:
every trainer drops a ready marker on a shared filesystem and spins until
all trainer_num markers exist. Storage-agnostic here: any
:class:`paddle_tpu.incubate.fleet.utils.fs.FS` (LocalFS for single-host
multiprocess runs, HDFSClient for clusters).
"""
from __future__ import annotations

import os
import time

from .fs import FS, LocalFS

__all__ = ["check_all_trainers_ready"]


def check_all_trainers_ready(ready_path: str, epoch: int,
                             trainer_id: int = None,
                             trainer_num: int = None,
                             fs: FS = None,
                             poll_interval: float = 0.2,
                             timeout: float = 600.0) -> None:
    if trainer_id is None or trainer_num is None:
        from ..base.fleet_base import fleet

        trainer_id = fleet.worker_index() if trainer_id is None else trainer_id
        trainer_num = fleet.worker_num() if trainer_num is None else trainer_num
    fs = fs or LocalFS()
    if not fs.is_dir(ready_path):
        fs.mkdirs(ready_path)
    marker = os.path.join(ready_path, f"ready.{epoch}.{trainer_id}.done")
    fs.touch(marker)
    deadline = time.time() + timeout
    while True:
        ready = [p for p in fs.ls(ready_path)
                 if os.path.basename(p).startswith(f"ready.{epoch}.")]
        if len(ready) >= trainer_num:
            return
        if time.time() > deadline:
            raise TimeoutError(
                f"barrier at {ready_path} epoch {epoch}: only "
                f"{len(ready)}/{trainer_num} trainers ready")
        time.sleep(poll_interval)
