"""KV HTTP server — parity with incubate/fleet/utils/http_server.py
(KVHandler/KVHTTPServer/KVServer): the rendezvous store fleet launchers use
to exchange endpoints/ready flags before collectives exist.

GET /scope/key -> value bytes; PUT /scope/key stores body; DELETE removes.
``should_stop`` mirrors the reference's size-contract (stop once every
scope holds its expected number of deletions).
"""
from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Dict

__all__ = ["KVHandler", "KVHTTPServer", "KVServer"]


class KVHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence default stderr spam
        pass

    def _parts(self):
        path = self.path.strip("/")
        if "/" not in path:
            return None, None
        scope, key = path.split("/", 1)
        return scope, key

    def do_GET(self):
        scope, key = self._parts()
        with self.server.kv_lock:
            val = self.server.kv.get(scope, {}).get(key)
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        scope, key = self._parts()
        n = int(self.headers.get("Content-Length", 0))
        if n > self.server.max_body_bytes:
            self.send_response(413)
            self.end_headers()
            return
        body = self.rfile.read(n)
        with self.server.kv_lock:
            self.server.kv.setdefault(scope, {})[key] = body
        self.send_response(200)
        self.end_headers()

    def do_DELETE(self):
        scope, key = self._parts()
        with self.server.kv_lock:
            if key in self.server.kv.get(scope, {}):
                del self.server.kv[scope][key]
                self.server.delete_kv.setdefault(scope, set()).add(key)
        self.send_response(200)
        self.end_headers()


class KVHTTPServer(HTTPServer):
    """Binds to PADDLE_KV_BIND_HOST (default all interfaces, matching the
    reference) — set it to the pod IP so only the training network can reach
    the rendezvous store; the port must be firewalled either way. PUT bodies
    are capped at PADDLE_KV_MAX_BODY_BYTES (default 64 MiB)."""

    def __init__(self, port, handler):
        host = os.environ.get("PADDLE_KV_BIND_HOST", "")
        super().__init__((host, port), handler)
        self.max_body_bytes = int(os.environ.get(
            "PADDLE_KV_MAX_BODY_BYTES", 64 << 20))
        self.kv: Dict[str, Dict[str, bytes]] = {}
        self.delete_kv: Dict[str, set] = {}
        self.kv_lock = threading.Lock()

    def get_deleted_size(self, scope):
        with self.kv_lock:
            return len(self.delete_kv.get(scope, ()))


class KVServer:
    """http_server.py:149 — background KV server with a stop contract:
    ``size`` maps scope -> number of DELETEs that signal completion."""

    def __init__(self, port: int, size: Dict[str, int] = None):
        self.http_server = KVHTTPServer(port, KVHandler)
        self.size = dict(size or {})
        self.listen_thread = None

    @property
    def port(self):
        return self.http_server.server_address[1]

    def start(self):
        self.listen_thread = threading.Thread(
            target=self.http_server.serve_forever, daemon=True)
        self.listen_thread.start()
        return self

    def stop(self):
        self.http_server.shutdown()
        if self.listen_thread is not None:
            self.listen_thread.join(timeout=5)
        self.http_server.server_close()

    def should_stop(self) -> bool:
        for scope, want in self.size.items():
            if self.http_server.get_deleted_size(scope) < want:
                return False
        return True

    shoud_stop = should_stop  # reference method name (sic)
