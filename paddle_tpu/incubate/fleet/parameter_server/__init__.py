"""PS-mode Fleet — parity with
fluid/incubate/fleet/parameter_server/distribute_transpiler/__init__.py
(DistributedTranspiler fleet: init_server/run_server/init_worker/
distributed_optimizer over the DistributeTranspiler).

Usage (reference PS recipe):

    fleet.init(role_maker)
    optimizer = fleet.distributed_optimizer(fluid.optimizer.SGDOptimizer(0.1))
    optimizer.minimize(loss)
    if fleet.is_server():
        fleet.init_server(); fleet.run_server()          # blocks
    else:
        fleet.init_worker()
        exe.run(fleet.main_program, feed=..., ...)
        fleet.stop_worker()
"""
from __future__ import annotations

from typing import Optional

from ....framework.executor import Executor
from ....framework.program import Program, default_main_program, default_startup_program
from ....transpiler.distribute_transpiler import (
    DistributeTranspiler, DistributeTranspilerConfig)
from ..base.fleet_base import Fleet
from ..base.role_maker import RoleMakerBase

__all__ = ["fleet", "ParameterServerOptimizer", "DistributedTranspiler"]


class DistributedTranspiler(Fleet):
    def __init__(self):
        super().__init__()
        self._transpiler: Optional[DistributeTranspiler] = None
        self.main_program: Optional[Program] = None
        self.startup_program: Optional[Program] = None
        self._server = None
        self._origin_main = None
        self._origin_startup = None

    # -- fleet lifecycle ----------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = ParameterServerOptimizer(self, optimizer,
                                                   strategy or
                                                   DistributeTranspilerConfig())
        return self._optimizer

    def _transpile(self, config: DistributeTranspilerConfig):
        t = DistributeTranspiler(config=config)
        t.transpile(
            trainer_id=self._role_maker.worker_index(),
            program=self._origin_main or default_main_program(),
            pservers=",".join(self._role_maker.get_pserver_endpoints()),
            trainers=self._role_maker.worker_num(),
            sync_mode=config.sync_mode,
            startup_program=self._origin_startup or default_startup_program(),
        )
        self._transpiler = t
        if self._role_maker.is_worker():
            self.main_program = t.get_trainer_program()
            self.startup_program = self._origin_startup or default_startup_program()
        else:
            ep = self._role_maker.get_current_server_endpoint()
            self.main_program = t.get_pserver_program(ep)
            self.startup_program = t.get_startup_program(ep)

    def init_worker(self):
        pass  # connections are lazy (PSClient wait-port on first send/recv)

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self, blocking: bool = True):
        """Run the pserver program (listen_and_serv host op)."""
        assert self.main_program is not None, "call minimize first"
        ls_op = self.main_program.global_block().ops[0]
        ls_op.attrs["blocking"] = blocking
        Executor().run(self.main_program)
        self._server = getattr(ls_op, "_server", None)
        return self._server

    def stop_worker(self):
        from ....distributed import PSClient
        tid = self._role_maker.worker_index()
        client = PSClient.instance(tid)
        client.complete(self._role_maker.get_pserver_endpoints())
        client.close()

    def stop_server(self):
        from ....distributed import PSClient
        client = PSClient.instance(self._role_maker.worker_index())
        for ep in self._role_maker.get_pserver_endpoints():
            client.stop_server(ep)

    def save_persistables(self, executor, dirname, main_program=None):
        from ....distributed import PSClient
        client = PSClient.instance(self._role_maker.worker_index())
        for ep in self._role_maker.get_pserver_endpoints():
            client.checkpoint_notify(ep, dirname)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io as fluid_io
        fluid_io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program or self._origin_main)


class ParameterServerOptimizer:
    """fleet.distributed_optimizer(...) for PS mode — parity with
    fleet/parameter_server/distribute_transpiler TranspilerOptimizer."""

    def __init__(self, fleet_: DistributedTranspiler, optimizer, config):
        self._fleet = fleet_
        self._optimizer = optimizer
        self._config = config

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        self._fleet._origin_main = loss.block.program
        self._fleet._origin_startup = startup_program
        self._fleet._transpile(self._config)
        return ops, params_grads


fleet = DistributedTranspiler()
