"""Role makers — parity with fluid/incubate/fleet/base/role_maker.py (1,115
LoC: RoleMakerBase, PaddleCloudRoleMaker reading the PADDLE_* env contract at
:501-536, UserDefinedRoleMaker, MPI/Gloo role makers for PS).

The TPU build keeps the same env contract; rendezvous/barrier duties the
reference delegates to Gloo/MPI are served by the jax.distributed coordinator.
"""
from __future__ import annotations

import os
from enum import IntEnum
from typing import List, Optional


class Role(IntEnum):
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._trainer_endpoints: List[str] = []
        self._server_endpoints: List[str] = []
        self._role: Optional[Role] = None
        self._current_id = -1
        self._generated = False

    def generate_role(self):
        raise NotImplementedError

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return len(self._trainer_endpoints)

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def get_trainer_endpoints(self) -> List[str]:
        return self._trainer_endpoints

    def get_pserver_endpoints(self) -> List[str]:
        return self._server_endpoints

    def get_current_server_endpoint(self) -> str:
        return self._server_endpoints[self._current_id]


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the PADDLE_* env contract (role_maker.py:501-536)."""

    def __init__(self, is_collective: bool = False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._generated:
            return
        if self._is_collective:
            self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
            self._trainer_endpoints = [e for e in eps.split(",") if e] or ["127.0.0.1:6070"]
            self._role = Role.WORKER
        else:
            port = os.getenv("PADDLE_PORT", "6070")
            pserver_ips = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = [e for e in pserver_ips.split(",") if e]
            role = os.getenv("TRAINING_ROLE", "TRAINER").upper()
            if role == "PSERVER":
                self._role = Role.SERVER
                cur = os.getenv("POD_IP", "127.0.0.1") + ":" + port
                self._current_id = (
                    self._server_endpoints.index(cur)
                    if cur in self._server_endpoints else 0
                )
            else:
                self._role = Role.WORKER
                self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            n = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
            self._trainer_endpoints = [f"trainer-{i}" for i in range(n)]
        self._generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._trainer_endpoints = [f"trainer-{i}" for i in range(worker_num)]
        self._server_endpoints = server_endpoints or []

    def generate_role(self):
        self._generated = True


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._trainer_endpoints = worker_endpoints or ["127.0.0.1:6070"]
        self._role = Role.WORKER

    def generate_role(self):
        self._generated = True
