"""Fleet abstract base — parity with fluid/incubate/fleet/base/fleet_base.py
(init/init_worker/init_server/distributed_optimizer surface)."""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

from .role_maker import PaddleCloudRoleMaker, RoleMakerBase


class Fleet(ABC):
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._optimizer = None
        self._is_initialized = False

    def init(self, role_maker: Optional[RoleMakerBase] = None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker(is_collective=True)
        role_maker.generate_role()
        self._role_maker = role_maker
        self._is_initialized = True
        return self

    # -- role info ----------------------------------------------------------
    def is_first_worker(self) -> bool:
        return self._role_maker.is_first_worker()

    def worker_index(self) -> int:
        return self._role_maker.worker_index()

    def worker_num(self) -> int:
        return self._role_maker.worker_num()

    def is_worker(self) -> bool:
        return self._role_maker.is_worker()

    def server_num(self) -> int:
        return self._role_maker.server_num()

    def server_index(self) -> int:
        return self._role_maker.server_index()

    def is_server(self) -> bool:
        return self._role_maker.is_server()

    def worker_endpoints(self):
        return self._role_maker.get_trainer_endpoints()

    def server_endpoints(self):
        return self._role_maker.get_pserver_endpoints()

    # -- lifecycle ----------------------------------------------------------
    @abstractmethod
    def init_worker(self):
        ...

    @abstractmethod
    def init_server(self, model_dir=None):
        ...

    @abstractmethod
    def run_server(self):
        ...

    @abstractmethod
    def stop_worker(self):
        ...

    @abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...

    @abstractmethod
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        ...

    @abstractmethod
    def save_persistables(self, executor, dirname, main_program=None):
        ...


class DistributedOptimizer(ABC):
    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ...
