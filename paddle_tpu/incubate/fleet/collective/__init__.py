"""Collective Fleet — parity with fluid/incubate/fleet/collective/__init__.py
(654 LoC: Collective fleet :64, DistributedStrategy :334, CollectiveOptimizer
:384 with minimize :586 that transpiles the program via
transpiler/collective.py GradAllReduce/LocalSGD).

TPU-native execution: CollectiveOptimizer.minimize appends backward+optimizer
ops as usual and then either (a) GSPMD mode — annotates the program for mesh
execution and lets XLA insert gradient all-reduces (the default; zero program
rewriting, hierarchical ICI/DCN allreduce for free), or (b) transpiler mode —
inserts explicit scale_loss_grad + c_allreduce_sum ops exactly like the
reference (use_transpiler=True / DistributedStrategy.mode "collective_ops"),
executed under shard_map with psum semantics. Both paths are tested for loss
parity with single-process runs.
"""
from __future__ import annotations

import os
from typing import Optional

from ....framework.compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from ....framework.program import default_main_program, default_startup_program
from ..base.fleet_base import DistributedOptimizer, Fleet
from ..base.role_maker import PaddleCloudRoleMaker


class DistributedStrategy(BuildStrategy):
    """Parity with collective/__init__.py:334 DistributedStrategy
    (extends BuildStrategy with fleet knobs)."""

    def __init__(self):
        super().__init__()
        self.mode = "gspmd"  # 'gspmd' (default) | 'collective_ops' | 'local_sgd'
        self.collective_mode = None
        self.nccl_comm_num = 1
        self.exec_strategy = ExecutionStrategy()
        self.use_local_sgd = False
        self.local_sgd_interval = 1
        self.use_amp = False
        self.amp_loss_scale = None  # None = decorate()'s per-dtype default
        self.use_recompute = False
        self.recompute_checkpoints = None
        self.forward_recompute = False
        self.use_hierarchical_allreduce = False  # XLA handles ICI/DCN layering


class Collective(Fleet):
    def __init__(self):
        super().__init__()
        self._main_programs = []

    def init_worker(self):
        from ....parallel.env import init_distributed_env

        if self.worker_num() > 1:
            init_distributed_env()

    def init_server(self, model_dir=None):
        raise NotImplementedError("Collective fleet has no servers")

    def run_server(self):
        raise NotImplementedError("Collective fleet has no servers")

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        return self._optimizer

    def compiled_program(self, main_program=None):
        program = main_program or default_main_program()
        return CompiledProgram(program).with_data_parallel()

    main_program = property(lambda self: default_main_program())

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io

        return io.save_inference_model(dirname, feeded_var_names, target_vars,
                                       executor, main_program,
                                       export_for_deployment=export_for_deployment)

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io

        return io.save_persistables(executor, dirname, main_program)

    # checkpoint save/load with retention — parity with
    # collective/__init__.py:206-333 (save_check_point / load_check_point /
    # clean_redundant_check_points over an FS abstraction)
    def save_check_point(self, executor, path, train_status,
                         main_program=None, fs=None, local_cache_path=".cache",
                         remain_all_checkpoint=False, max_no=3):
        import json

        from .... import io

        os.makedirs(path, exist_ok=True)
        existing = sorted(
            int(d.rsplit("_", 1)[-1])
            for d in os.listdir(path) if d.startswith("checkpoint_")
        )
        no = (existing[-1] + 1) if existing else 0
        cdir = os.path.join(path, f"checkpoint_{no}")
        os.makedirs(cdir, exist_ok=True)
        io.save_persistables(executor, cdir, main_program)
        with open(os.path.join(cdir, "train_status.json"), "w") as f:
            json.dump(train_status, f)
        if not remain_all_checkpoint:
            for old in existing[: max(0, len(existing) + 1 - max_no)]:
                import shutil

                shutil.rmtree(os.path.join(path, f"checkpoint_{old}"),
                              ignore_errors=True)
        return no

    def load_check_point(self, executor, path, trainer_id=None,
                         main_program=None, fs=None, local_cache_path=".cache",
                         ignore_empty=True):
        import json

        from .... import io

        if not os.path.isdir(path):
            if ignore_empty:
                return None
            raise FileNotFoundError(path)
        nos = sorted(
            int(d.rsplit("_", 1)[-1])
            for d in os.listdir(path) if d.startswith("checkpoint_")
        )
        if not nos:
            if ignore_empty:
                return None
            raise FileNotFoundError(f"no checkpoints under {path}")
        cdir = os.path.join(path, f"checkpoint_{nos[-1]}")
        io.load_persistables(executor, cdir, main_program)
        with open(os.path.join(cdir, "train_status.json")) as f:
            return json.load(f)


fleet = Collective()


class CollectiveOptimizer(DistributedOptimizer):
    """Parity with CollectiveOptimizer (collective/__init__.py:384)."""

    def __init__(self, optimizer, strategy: Optional[DistributedStrategy] = None):
        super().__init__(optimizer, strategy or DistributedStrategy())

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        strategy = self._strategy
        inner = self._optimizer

        if strategy.use_recompute:
            from ....optimizer import RecomputeOptimizer

            rec = RecomputeOptimizer(inner)
            rec._set_checkpoints(strategy.recompute_checkpoints)
            inner = rec

        if strategy.use_amp:
            from ....contrib.mixed_precision import decorate

            # only forward a loss scale the user actually set — decorate()
            # picks the right default per dtype (1.0 bf16 / 2**15 fp16)
            if strategy.amp_loss_scale is None:
                inner = decorate(inner)
            else:
                inner = decorate(inner,
                                 init_loss_scaling=strategy.amp_loss_scale)

        optimize_ops, params_grads = inner.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        program = loss.block.program
        if strategy.mode == "collective_ops":
            from ....transpiler.collective import GradAllReduce

            t = GradAllReduce()
            t.transpile(
                startup_program=startup_program or default_startup_program(),
                main_program=program,
                rank=fleet.worker_index() if fleet._is_initialized else 0,
                endpoints=fleet.worker_endpoints() if fleet._is_initialized else [],
                current_endpoint="", wait_port=False,
                params_grads=params_grads,
            )
            program._annotations["mesh"] = {
                "mode": "shard_map", "axes": [("dp", -1)], "data_axis": "dp",
                "ring_axes": {0: "dp"},
            }
            if strategy.sync_batch_norm:
                from ....framework.compiler import rewrite_sync_batch_norm

                rewrite_sync_batch_norm(program)
        elif strategy.mode == "local_sgd" or strategy.use_local_sgd:
            from ....transpiler.collective import LocalSGD

            t = LocalSGD(interval=strategy.local_sgd_interval)
            t.transpile(
                startup_program=startup_program or default_startup_program(),
                main_program=program, rank=0, endpoints=[],
                current_endpoint="", wait_port=False,
                params_grads=params_grads,
            )
            program._annotations["mesh"] = {
                "mode": "shard_map", "axes": [("dp", -1)], "data_axis": "dp",
                "ring_axes": {0: "dp"},
            }
        else:  # gspmd
            program._annotations["mesh"] = {
                "mode": "gspmd", "axes": [("dp", -1)], "data_axis": "dp",
            }
        return optimize_ops, params_grads
