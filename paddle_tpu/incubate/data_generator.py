"""DataGenerator — parity with python/paddle/fluid/incubate/data_generator/
(__init__.py:21): the authoring API that turns user records into the
MultiSlot text the Dataset engine (and its C++ parser) consumes.

Users override ``generate_sample(line)`` (returning an iterator of
``[(slot_name, [feasigns...]), ...]``) and optionally ``generate_batch``;
``run_from_stdin`` / ``run_from_memory`` stream the encoded lines, exactly
like the reference's mapreduce-side usage.
"""
from __future__ import annotations

import sys
from typing import Iterable, List, Optional, Tuple

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit: int):
        if not isinstance(line_limit, int) or line_limit < 1:
            raise ValueError("line_limit must be a positive int")
        self._line_limit = line_limit

    def set_batch(self, batch_size: int):
        self.batch_size_ = batch_size

    # -- user hooks --------------------------------------------------------
    def generate_sample(self, line):
        raise NotImplementedError(
            "rewrite generate_sample to return an iterator of "
            "[(name, [feasign, ...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _gen_str(self, line) -> str:
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    # -- drivers -----------------------------------------------------------
    def _run(self, lines: Iterable[str], out) -> int:
        batch_samples = []
        n_out = 0
        for i, line in enumerate(lines):
            if self._line_limit is not None and i >= self._line_limit:
                break
            gen = self.generate_sample(line)
            for sample in gen():
                if sample is None:
                    continue
                batch_samples.append(sample)
                if len(batch_samples) == self.batch_size_:
                    for processed in self.generate_batch(batch_samples)():
                        out.write(self._gen_str(processed))
                        n_out += 1
                    batch_samples = []
        if batch_samples:
            for processed in self.generate_batch(batch_samples)():
                out.write(self._gen_str(processed))
                n_out += 1
        return n_out

    def run_from_stdin(self):
        """__init__.py:101 — encode stdin lines to stdout (the hadoop
        streaming / dataset preprocessing entry point)."""
        return self._run(sys.stdin, sys.stdout)

    def run_from_memory(self):
        """__init__.py:67 — generate without an input stream (the user's
        generate_sample ignores its line argument)."""
        return self._run([None], sys.stdout)

    def run_from_lines(self, lines: Iterable[str], out=None):
        """Convenience for tests/pipelines: encode an iterable, return the
        emitted text when ``out`` is None."""
        import io

        buf = out or io.StringIO()
        self._run(lines, buf)
        return buf.getvalue() if out is None else None


class MultiSlotDataGenerator(DataGenerator):
    """Encode ``[(name, [feasigns])...]`` as MultiSlot text:
    ``<n> v1 .. vn`` per slot, space-joined (data_feed.cc MultiSlotDataFeed
    line grammar; slot name order must match the Dataset's use-var list).
    The first sample pins each slot's type (int stays int, any float makes
    the slot float) and the slot order — later samples must conform."""

    def _gen_str(self, line) -> str:
        if not isinstance(line, (list, tuple)):
            raise ValueError("expected [(name, [feasign...]), ...]")
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                ty = "d"
                for e in elements:
                    if isinstance(e, float):
                        ty = "f"
                        break
                self._proto_info.append((name, ty))
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"sample has {len(line)} slots, first sample had "
                    f"{len(self._proto_info)}")
            for (name, elements), (pname, pty) in zip(line,
                                                      self._proto_info):
                if name != pname:
                    raise ValueError(
                        f"slot order changed: {name!r} vs {pname!r}")
                if pty == "d" and any(isinstance(e, float)
                                      for e in elements):
                    raise ValueError(
                        f"slot {name!r} was int, got float feasign")
        parts: List[str] = []
        for name, elements in line:
            parts.append(str(len(elements)))
            for e in elements:
                parts.append(str(e))
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Pre-stringified variant: elements are already strings."""

    def _gen_str(self, line) -> str:
        parts: List[str] = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"
