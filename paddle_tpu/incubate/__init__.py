
from . import data_generator  # noqa: F401
