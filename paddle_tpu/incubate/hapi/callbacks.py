"""hapi callbacks — parity with incubate/hapi/callbacks.py (subset: the
config/train-loop hook surface, ProgBarLogger, ModelCheckpoint)."""
from __future__ import annotations

import os

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = dict(params or {})

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq: int = 10, verbose: int = 1):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self.verbose and self._step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Epoch {self._epoch} step {self._step}: {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                              if isinstance(v, float))
            print(f"Eval: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


def config_callbacks(callbacks=None, model=None, log_freq=10, verbose=1,
                     save_dir=None, save_freq=1):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.insert(0, ProgBarLogger(log_freq, verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    for c in cbs:
        c.set_model(model)
    return CallbackList(cbs)
