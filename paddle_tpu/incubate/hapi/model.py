"""hapi Model — parity with incubate/hapi/model.py (Model, Input,
prepare/fit/evaluate/predict/save/load).

The reference Model adapts one network to both dygraph and static modes; here
the static Program path IS the TPU-native fast path (whole-program XLA), so
Model builds three programs from one network builder:
  train  = forward + loss + metrics + optimizer
  eval   = forward + loss + metrics   (clone-for-test)
  predict= forward
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ... import io as fluid_io
from ... import layers
from ...framework.executor import Executor, Scope
from ...framework.core import XLAPlace
from ...framework.program import Program, program_guard
from ...reader import DataLoader, Dataset
from .callbacks import config_callbacks

__all__ = ["Model", "Input"]


class Input:
    """hapi Input descriptor (incubate/hapi/input.py): name/shape/dtype of a
    feed slot; batch dim None/-1."""

    def __init__(self, shape: Sequence[int], dtype: str = "float32",
                 name: Optional[str] = None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def to_var(self):
        shape = [-1 if d in (None, -1) else int(d) for d in self.shape]
        return layers.data(self.name, shape[1:] if shape and shape[0] == -1
                           else shape, dtype=self.dtype,
                           append_batch_size=(bool(shape) and shape[0] == -1))


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _iter_data(data, feed_names: List[str], batch_size: int, shuffle: bool):
    """Normalize user data into an iterator of feed dicts.  Accepts a
    DataLoader, a map-style Dataset, a (x, y) tuple/list of arrays, or any
    iterable of feed dicts / field tuples."""
    if isinstance(data, DataLoader):
        for batch in data:
            yield (batch if isinstance(batch, dict)
                   else dict(zip(feed_names, batch)))
        return
    if isinstance(data, Dataset):
        dl = DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        for batch in dl:
            yield dict(zip(feed_names, batch))
        return
    if isinstance(data, (tuple, list)) and data and hasattr(data[0], "shape"):
        n = data[0].shape[0]
        idx = np.random.permutation(n) if shuffle else np.arange(n)
        for s in range(0, n, batch_size):
            sel = idx[s:s + batch_size]
            yield {name: np.asarray(arr)[sel]
                   for name, arr in zip(feed_names, data)}
        return
    for batch in data:  # iterable of dicts or tuples
        yield (batch if isinstance(batch, dict)
               else dict(zip(feed_names, batch)))


class Model:
    def __init__(self, network: Callable, inputs: Sequence[Input],
                 labels: Optional[Sequence[Input]] = None):
        self._network = network
        self._input_descs = _to_list(inputs)
        self._label_descs = _to_list(labels)
        self._place = XLAPlace(0)
        self._exe = Executor(self._place)
        self._scope = Scope()
        self._prepared = False
        self._startup_ran = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss_function=None, metrics=None):
        self._metrics = _to_list(metrics)
        self._train_prog = Program()
        self._startup_prog = Program()
        from ...framework import unique_name
        # fresh name namespace per Model so save/load match across instances
        with unique_name.guard():
            with program_guard(self._train_prog, self._startup_prog):
                in_vars = [d.to_var() for d in self._input_descs]
                lab_vars = [d.to_var() for d in self._label_descs]
                outs = _to_list(self._network(*in_vars))
                self._feed_names = [v.name for v in in_vars + lab_vars]
                self._out_names = [v.name for v in outs]
                loss_var = None
                metric_vars = []
                if loss_function is not None:
                    loss_var = loss_function(outs, lab_vars)
                for m in self._metrics:
                    # in-graph accuracy against label 0 (hapi Accuracy pattern)
                    metric_vars.append(layers.accuracy(outs[0], lab_vars[0]))
            # eval program = train program before optimizer ops, test clone
            self._eval_prog = self._train_prog.clone(for_test=True)
            self._pred_prog = fluid_io.prune_program(
                self._eval_prog, [d.name for d in self._input_descs],
                self._out_names)
            self._loss_name = loss_var.name if loss_var is not None else None
            self._metric_names = [v.name for v in metric_vars]
            if optimizer is not None and loss_var is not None:
                with program_guard(self._train_prog, self._startup_prog):
                    optimizer.minimize(loss_var)
        self._optimizer = optimizer
        self._prepared = True

    def _ensure_startup(self):
        if not self._startup_ran:
            self._exe.run(self._startup_prog, scope=self._scope)
            self._startup_ran = True

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size: int = 1,
            epochs: int = 1, eval_freq: int = 1, log_freq: int = 10,
            save_dir: Optional[str] = None, save_freq: int = 1,
            verbose: int = 1, shuffle: bool = True, callbacks=None):
        assert self._prepared, "call prepare() first"
        self._ensure_startup()
        cbks = config_callbacks(callbacks, model=self, log_freq=log_freq,
                                verbose=verbose, save_dir=save_dir,
                                save_freq=save_freq)
        fetches = ([self._loss_name] if self._loss_name else []) \
            + self._metric_names
        history: Dict[str, List[float]] = {}
        cbks.on_train_begin(None)
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch, None)
            logs: Dict[str, Any] = {}
            for step, feed in enumerate(_iter_data(
                    train_data, self._feed_names, batch_size, shuffle)):
                cbks.on_train_batch_begin(step, None)
                vals = self._exe.run(self._train_prog, feed=feed,
                                     fetch_list=fetches, scope=self._scope)
                logs = {name: float(np.asarray(v).mean())
                        for name, v in zip(
                            (["loss"] if self._loss_name else [])
                            + [f"acc_{i}" for i in
                               range(len(self._metric_names))], vals)}
                cbks.on_train_batch_end(step, logs)
            for k, v in logs.items():
                history.setdefault(k, []).append(v)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size,
                                          verbose=0, callbacks=cbks)
                for k, v in eval_logs.items():
                    history.setdefault("eval_" + k, []).append(v)
            cbks.on_epoch_end(epoch, logs)
        cbks.on_train_end(None)
        return history

    def evaluate(self, eval_data, batch_size: int = 1, verbose: int = 1,
                 callbacks=None):
        assert self._prepared, "call prepare() first"
        self._ensure_startup()
        fetches = ([self._loss_name] if self._loss_name else []) \
            + self._metric_names
        names = (["loss"] if self._loss_name else []) \
            + [f"acc_{i}" for i in range(len(self._metric_names))]
        sums = np.zeros(len(fetches))
        count = 0
        for feed in _iter_data(eval_data, self._feed_names, batch_size, False):
            vals = self._exe.run(self._eval_prog, feed=feed,
                                 fetch_list=fetches, scope=self._scope)
            bs = next(iter(feed.values())).shape[0]
            sums += np.array([float(np.asarray(v).mean()) for v in vals]) * bs
            count += bs
        logs = dict(zip(names, (sums / max(count, 1)).tolist()))
        if callbacks is not None:
            callbacks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size: int = 1):
        assert self._prepared, "call prepare() first"
        self._ensure_startup()
        input_names = [d.name for d in self._input_descs]
        outs: List[List[np.ndarray]] = [[] for _ in self._out_names]
        for feed in _iter_data(test_data, input_names, batch_size, False):
            feed = {k: v for k, v in feed.items() if k in input_names}
            vals = self._exe.run(self._pred_prog, feed=feed,
                                 fetch_list=self._out_names, scope=self._scope)
            for o, v in zip(outs, vals):
                o.append(np.asarray(v))
        return [np.concatenate(o) for o in outs]

    # ------------------------------------------------------------------
    def save(self, path: str):
        import os
        os.makedirs(path, exist_ok=True)
        from ...framework.executor import scope_guard
        with scope_guard(self._scope):
            fluid_io.save_persistables(self._exe, path, self._train_prog)

    def load(self, path: str, skip_mismatch: bool = False):
        self._ensure_startup()
        from ...framework.executor import scope_guard
        with scope_guard(self._scope):
            fluid_io.load_persistables(self._exe, path, self._train_prog)

    def parameters(self):
        from ...framework.program import Parameter
        return [v for v in self._train_prog.global_block().vars.values()
                if isinstance(v, Parameter)]
