"""High-level Model API — parity with paddle/incubate/hapi (Model.fit era).

``Model`` wraps a static-graph network builder; prepare() attaches an
optimizer/loss/metrics, fit()/evaluate()/predict() drive the Executor with
whole-program XLA compilation under the hood.
"""
from .model import Model, Input  # noqa: F401
from . import loss  # noqa: F401
from .loss import CrossEntropy, SoftmaxWithCrossEntropy, MSE  # noqa: F401
from .callbacks import Callback, ProgBarLogger, ModelCheckpoint  # noqa: F401
