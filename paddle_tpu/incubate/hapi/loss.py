"""hapi loss classes — parity with incubate/hapi/loss.py.

A Loss builds graph ops from (outputs, labels) variable lists and returns a
scalar loss variable.
"""
from __future__ import annotations

from ... import layers

__all__ = ["Loss", "CrossEntropy", "SoftmaxWithCrossEntropy", "MSE"]


class Loss:
    def forward(self, outputs, labels):
        raise NotImplementedError

    def __call__(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        return self.forward(list(outs), list(labs))


class CrossEntropy(Loss):
    """Expects softmax-probability outputs (reference hapi CrossEntropy)."""

    def forward(self, outputs, labels):
        return layers.reduce_mean(
            layers.cross_entropy(outputs[0], labels[0]))


class SoftmaxWithCrossEntropy(Loss):
    """Expects raw logits — fused, numerically-stable path."""

    def forward(self, outputs, labels):
        return layers.reduce_mean(
            layers.softmax_with_cross_entropy(outputs[0], labels[0]))


class MSE(Loss):
    def forward(self, outputs, labels):
        return layers.reduce_mean(
            layers.square_error_cost(outputs[0], labels[0]))
