"""paddle.incubate.complex.tensor.linalg — parity with
python/paddle/incubate/complex/tensor/linalg.py (matmul:22)."""
from __future__ import annotations

import jax.numpy as jnp

from ..helper import complex_variable_exists
from ..tensor_base import ComplexVariable, _raw

__all__ = ["matmul"]


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    complex_variable_exists([x, y], "matmul")
    a = jnp.asarray(_raw(x))
    b = jnp.asarray(_raw(y))
    if transpose_x:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_y:
        b = jnp.swapaxes(b, -1, -2)
    out = jnp.matmul(a, b)
    if alpha != 1.0:
        out = out * alpha
    return ComplexVariable(out)
