from .linalg import matmul  # noqa: F401
from .manipulation import reshape, transpose  # noqa: F401
from .math import (  # noqa: F401
    elementwise_add, elementwise_div, elementwise_mul, elementwise_sub,
    kron, sum, trace,
)

__all__ = ["elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "trace", "sum", "kron", "matmul", "reshape",
           "transpose"]
