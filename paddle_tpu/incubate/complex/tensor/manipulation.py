"""paddle.incubate.complex.tensor.manipulation — parity with
python/paddle/incubate/complex/tensor/manipulation.py (reshape:26,
transpose:112)."""
from __future__ import annotations

import jax.numpy as jnp

from ..helper import complex_variable_exists
from ..tensor_base import ComplexVariable, _raw

__all__ = ["reshape", "transpose"]


def reshape(x, shape, inplace=False, name=None):
    complex_variable_exists([x], "reshape")
    return ComplexVariable(jnp.reshape(jnp.asarray(_raw(x)), shape))


def transpose(x, perm, name=None):
    complex_variable_exists([x], "transpose")
    return ComplexVariable(jnp.transpose(jnp.asarray(_raw(x)), perm))
