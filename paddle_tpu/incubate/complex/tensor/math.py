"""paddle.incubate.complex.tensor.math — parity with
python/paddle/incubate/complex/tensor/math.py (elementwise_add:32,
elementwise_sub:83, elementwise_mul:134, elementwise_div:188, trace:239,
sum:276, kron:339).

Each op is ONE native complex XLA computation (the reference assembles
four real-kernel calls per complex multiply)."""
from __future__ import annotations

import jax.numpy as jnp

from ..helper import complex_variable_exists
from ..tensor_base import ComplexVariable, _raw

__all__ = ["elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "trace", "sum", "kron"]


def _binary(name, fn):
    def op(x, y, axis=-1, name_=None, **kw):
        complex_variable_exists([x, y], name)
        return ComplexVariable(fn(jnp.asarray(_raw(x)),
                                  jnp.asarray(_raw(y))))
    op.__name__ = name
    op.__doc__ = f"complex {name} (single fused XLA op)."
    return op


elementwise_add = _binary("elementwise_add", jnp.add)
elementwise_sub = _binary("elementwise_sub", jnp.subtract)
elementwise_mul = _binary("elementwise_mul", jnp.multiply)
elementwise_div = _binary("elementwise_div", jnp.divide)


def trace(input, offset=0, dim1=0, dim2=1, name=None):
    complex_variable_exists([input], "trace")
    return ComplexVariable(jnp.trace(jnp.asarray(_raw(input)),
                                     offset=offset, axis1=dim1, axis2=dim2))


def sum(input, dim=None, keep_dim=False, name=None):
    complex_variable_exists([input], "sum")
    axis = tuple(dim) if isinstance(dim, (list, tuple)) else dim
    return ComplexVariable(jnp.sum(jnp.asarray(_raw(input)), axis=axis,
                                   keepdims=keep_dim))


def kron(x, y, name=None):
    complex_variable_exists([x, y], "kron")
    return ComplexVariable(jnp.kron(jnp.asarray(_raw(x)),
                                    jnp.asarray(_raw(y))))
