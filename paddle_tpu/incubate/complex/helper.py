"""paddle.incubate.complex.helper — parity with
python/paddle/incubate/complex/helper.py."""
from .tensor_base import ComplexVariable

__all__ = ["is_complex", "is_real", "complex_variable_exists"]


def is_complex(x) -> bool:
    if isinstance(x, ComplexVariable):
        return True
    import jax.numpy as jnp

    v = getattr(x, "value", x)
    return hasattr(v, "dtype") and jnp.issubdtype(v.dtype,
                                                  jnp.complexfloating)


def is_real(x) -> bool:
    return not is_complex(x) and hasattr(getattr(x, "value", x), "dtype")


def complex_variable_exists(inputs, layer_name):
    if any(is_complex(i) for i in inputs):
        return
    err = ("At least one inputs of layer complex." if len(inputs) > 1
           else "The input of layer complex.")
    raise ValueError(err + layer_name +
                     "() must be ComplexVariable, please use the layer "
                     "for real number instead.")
