"""paddle.incubate.complex — parity with
python/paddle/incubate/complex/__init__.py.

TPU-native design departure: the reference builds ComplexVariable as a
(real, imag) PAIR of fluid Variables because its tensors have no complex
dtype (framework.py ComplexVariable). XLA/jax support complex64/128
natively, so here a ComplexVariable wraps ONE complex array — every op is
a single fused XLA computation instead of four real-arithmetic kernels.
"""
from . import tensor  # noqa: F401
from .helper import is_complex, is_real  # noqa: F401
from .tensor import (  # noqa: F401
    elementwise_add, elementwise_div, elementwise_mul, elementwise_sub,
    kron, matmul, reshape, sum, trace, transpose,
)
from .tensor_base import ComplexVariable  # noqa: F401

__all__ = list(tensor.__all__)
