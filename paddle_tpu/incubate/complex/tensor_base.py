"""ComplexVariable — parity with the reference's framework.ComplexVariable
(python/paddle/fluid/framework.py), holding ONE native complex array
instead of a (real, imag) pair."""
from __future__ import annotations

import numpy as np


class ComplexVariable:
    """An eager complex tensor. Construct from a complex ndarray, or from
    real + imag parts (the reference's layout)."""

    def __init__(self, value, imag=None, name=None):
        value = _raw(value)
        if imag is not None:
            value = np.asarray(value) + 1j * np.asarray(_raw(imag))
        import jax.numpy as jnp

        v = jnp.asarray(value)
        if not jnp.issubdtype(v.dtype, jnp.complexfloating):
            v = v.astype(jnp.complex64)
        self.value = v
        self.name = name

    @property
    def real(self):
        return self.value.real

    @property
    def imag(self):
        return self.value.imag

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    def numpy(self):
        return np.asarray(self.value)

    def conj(self):
        return ComplexVariable(self.value.conj())

    def __repr__(self):
        return f"ComplexVariable(shape={self.shape})\n{self.value}"


def _raw(v):
    if isinstance(v, ComplexVariable):
        return v.value
    return getattr(v, "value", v)
