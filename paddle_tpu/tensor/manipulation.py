"""paddle.tensor.manipulation — parity with
python/paddle/tensor/manipulation.py (flip:54, roll:107, stack:181,
split:294, squeeze:433, unsqueeze:512, gather:595, unbind:669).
"""
from __future__ import annotations

from ._dispatch import dispatch, in_dygraph_mode

__all__ = [
    "cast", "concat", "expand", "expand_as", "flatten", "gather",
    "gather_nd", "reshape", "reverse", "scatter", "scatter_nd_add",
    "scatter_nd", "shard_index", "slice", "split", "squeeze", "stack",
    "strided_slice", "transpose", "unique", "unique_with_counts",
    "unsqueeze", "unstack", "flip", "unbind", "roll",
]


def cast(x, dtype):
    return dispatch("cast", {"X": x}, {"out_dtype": str(dtype)},
                    out_dtypes=str(dtype))


def concat(input, axis=0, name=None):
    return dispatch("concat", {"X": list(input)}, {"axis": int(axis)})


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    if in_dygraph_mode():
        out = dispatch("reshape2", {"X": x}, {"shape": list(shape)})
        return dispatch(act, {"X": out}) if act else out
    from ..layers import tensor as _lt
    return _lt.reshape(x, shape, actual_shape=actual_shape, act=act,
                       inplace=inplace, name=name)


def flatten(x, axis=1, name=None):
    return dispatch("flatten2", {"X": x}, {"axis": int(axis)})


def transpose(x, perm, name=None):
    return dispatch("transpose2", {"X": x}, {"axis": list(perm)})


def squeeze(input, axes, out=None, name=None):
    """manipulation.py:433."""
    return dispatch("squeeze2", {"X": input}, {"axes": list(axes)})


def unsqueeze(input, axes, out=None, name=None):
    """manipulation.py:512."""
    axes = [axes] if isinstance(axes, int) else list(axes)
    return dispatch("unsqueeze2", {"X": input}, {"axes": axes})


def stack(x, axis=0, out=None, name=None):
    """manipulation.py:181."""
    return dispatch("stack", {"X": list(x)}, {"axis": int(axis)},
                    out_slots=("Y",))


def unstack(x, axis=0, num=None):
    n = num or x.shape[axis]
    out = dispatch("unstack", {"X": x}, {"axis": int(axis), "num": int(n)},
                   out_counts={"Y": int(n)}, out_slots=("Y",))
    return list(out) if isinstance(out, (list, tuple)) else [out]


def split(input, num_or_sections, dim=-1, name=None):
    """manipulation.py:294."""
    axis = int(dim)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": axis}
    else:
        secs = [int(s) for s in num_or_sections]
        n = len(secs)
        attrs = {"sections": secs, "axis": axis}
    out = dispatch("split", {"X": input}, attrs, out_counts={"Out": n})
    return list(out) if isinstance(out, (list, tuple)) else [out]


def unbind(input, axis=0):
    """manipulation.py:669."""
    n = input.shape[axis]
    out = dispatch("unbind", {"X": input}, {"axis": int(axis)},
                   out_counts={"Out": int(n)})
    return list(out) if isinstance(out, (list, tuple)) else [out]


def gather(input, index, overwrite=True):
    """manipulation.py:595."""
    return dispatch("gather", {"X": input, "Index": index})


def gather_nd(input, index, name=None):
    return dispatch("gather_nd", {"X": input, "Index": index})


def scatter(input, index, updates, overwrite=True, name=None):
    return dispatch("scatter", {"X": input, "Ids": index,
                                "Updates": updates},
                    {"overwrite": bool(overwrite)})


def scatter_nd_add(ref, index, updates, name=None):
    return dispatch("scatter_nd_add", {"X": ref, "Index": index,
                                       "Updates": updates})


def scatter_nd(index, updates, shape, name=None):
    return dispatch("scatter_nd", {"Index": index, "Updates": updates},
                    {"shape": [int(s) for s in shape]})


def expand(x, expand_times, name=None):
    return dispatch("expand", {"X": x},
                    {"expand_times": [int(t) for t in expand_times]})


def expand_as(x, target_tensor, name=None):
    return dispatch("expand_as", {"X": x, "target_tensor": target_tensor})


def reverse(x, axis):
    axis = [axis] if isinstance(axis, int) else list(axis)
    return dispatch("reverse", {"X": x}, {"axis": axis})


def flip(input, dims, name=None):
    """manipulation.py:54."""
    dims = [dims] if isinstance(dims, int) else list(dims)
    return dispatch("flip", {"X": input}, {"axis": dims})


def roll(input, shifts, dims=None):
    """manipulation.py:107."""
    shifts = [shifts] if isinstance(shifts, int) else list(shifts)
    attrs = {"shifts": shifts}
    attrs["axis"] = ([dims] if isinstance(dims, int) else list(dims)) \
        if dims is not None else []
    return dispatch("roll", {"X": input}, attrs)


def slice(input, axes, starts, ends):
    return dispatch("slice", {"Input": input},
                    {"axes": list(axes), "starts": list(starts),
                     "ends": list(ends)})


def strided_slice(input, axes, starts, ends, strides):
    return dispatch("strided_slice", {"Input": input},
                    {"axes": list(axes), "starts": list(starts),
                     "ends": list(ends), "strides": list(strides)})


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return dispatch("shard_index", {"X": input},
                    {"index_num": int(index_num), "nshards": int(nshards),
                     "shard_id": int(shard_id),
                     "ignore_value": int(ignore_value)})


def unique(x, dtype="int32"):
    """Host-side op (dynamic shape) — not for jit regions on TPU."""
    return dispatch("unique", {"X": x}, out_slots=("Out", "Index"),
                    stop_gradient=True)


def unique_with_counts(x, dtype="int32"):
    return dispatch("unique_with_counts", {"X": x},
                    out_slots=("Out", "Index", "Count"), stop_gradient=True)
