"""paddle.tensor.creation — parity with python/paddle/tensor/creation.py
(full:500, full_like:57, arange:586, tril:693, triu:770, meshgrid:847,
ones:213, zeros:325, eye:437, linspace:124).

Every function works in both dygraph (eager lowering) and static (Program
append) mode via the registry dispatch — see _dispatch.py.
"""
from __future__ import annotations

import numpy as np

from ._dispatch import dispatch, in_dygraph_mode

__all__ = [
    "create_tensor", "crop_tensor", "diag", "eye", "fill_constant",
    "linspace", "ones", "ones_like", "zeros", "zeros_like", "arange",
    "full", "full_like", "triu", "tril", "meshgrid",
]


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    if in_dygraph_mode():
        return dispatch("fill_constant", {},
                        {"shape": [int(s) for s in shape],
                         "dtype": str(dtype), "value": value})
    from ..layers import tensor as _lt
    return _lt.fill_constant(shape, dtype, value, out=out, name=name)


def full(shape, fill_value, out=None, dtype=None, device=None,
         stop_gradient=True, name=None):
    """creation.py:500 — constant tensor; dtype defaults from fill_value."""
    if dtype is None:
        dtype = ("bool" if isinstance(fill_value, bool) else
                 "int64" if isinstance(fill_value, int) else "float32")
    return fill_constant(shape, dtype, fill_value, out=out, name=name)


def full_like(input, fill_value, out=None, dtype=None, device=None,
              stop_gradient=True, name=None):
    return dispatch("fill_any_like", {"X": input},
                    {"value": float(fill_value),
                     "dtype": str(dtype) if dtype else None},
                    out_dtypes=str(dtype) if dtype else None,
                    stop_gradient=stop_gradient)


def ones(shape, dtype=None, out=None, device=None):
    return fill_constant(shape, dtype or "float32", 1.0, out=out)


def zeros(shape, dtype=None, out=None, device=None):
    return fill_constant(shape, dtype or "float32", 0.0, out=out)


def ones_like(input, dtype=None, device=None, name=None):
    return full_like(input, 1.0, dtype=dtype, name=name)


def zeros_like(input, dtype=None, device=None, name=None):
    return full_like(input, 0.0, dtype=dtype, name=name)


def arange(start, end=None, step=1, dtype=None, name=None):
    """creation.py:586 — paddle.arange(start[, end, step])."""
    if end is None:
        start, end = 0, start
    dtype = str(dtype or "float32")
    if in_dygraph_mode():
        out = dispatch("range", {"Start": np.asarray(start),
                                 "End": np.asarray(end),
                                 "Step": np.asarray(step)})
        return cast(out, dtype) if str(out.dtype) != dtype else out
    from ..layers import tensor as _lt
    return _lt.range(start, end, step, dtype)


def linspace(start, stop, num, dtype="float32", out=None, device=None,
             name=None):
    if in_dygraph_mode():
        return dispatch("linspace", {"Start": np.asarray(start, np.float32),
                                     "Stop": np.asarray(stop, np.float32),
                                     "Num": np.asarray(num, np.int32)},
                        {"dtype": str(dtype)}, out_dtypes=str(dtype))
    from ..layers import tensor as _lt
    return _lt.linspace(start, stop, num, dtype)


def eye(num_rows, num_columns=None, out=None, dtype="float32", stop_gradient=True,
        name=None):
    return dispatch("eye", {},
                    {"num_rows": int(num_rows),
                     "num_columns": int(num_columns if num_columns is not None
                                        else num_rows),
                     "dtype": str(dtype)},
                    out_dtypes=str(dtype), stop_gradient=stop_gradient)


def diag(diagonal):
    return dispatch("diag", {"Diagonal": diagonal})


def tril(input, diagonal=0, name=None):
    """creation.py:693 — lower-triangular part."""
    return dispatch("tril_triu", {"X": input},
                    {"diagonal": int(diagonal), "lower": True})


def triu(input, diagonal=0, name=None):
    """creation.py:770 — upper-triangular part."""
    return dispatch("tril_triu", {"X": input},
                    {"diagonal": int(diagonal), "lower": False})


def meshgrid(input, name=None):
    """creation.py:847 — N 1-D tensors -> N broadcast N-D tensors."""
    n = len(input)
    out = dispatch("meshgrid", {"X": list(input)}, {},
                   out_counts={"Out": n})
    return list(out) if isinstance(out, (list, tuple)) else [out]


def create_tensor(dtype, name=None, persistable=False):
    from ..layers import tensor as _lt
    return _lt.create_tensor(dtype, name=name, persistable=persistable)


def crop_tensor(x, shape=None, offsets=None, name=None):
    from ..layers import extras as _le
    return _le.crop_tensor(x, shape=shape, offsets=offsets, name=name)


def cast(x, dtype):
    return dispatch("cast", {"X": x},
                    {"out_dtype": str(dtype)}, out_dtypes=str(dtype))
