"""paddle.tensor.search — parity with python/paddle/tensor/search.py
(argmax:45, index_select:138, nonzero:202, sort:289, where:381,
index_sample:459).
"""
from __future__ import annotations

import numpy as np

from ._dispatch import dispatch, in_dygraph_mode

__all__ = ["argmax", "argmin", "argsort", "has_inf", "has_nan", "topk",
           "where", "index_select", "nonzero", "sort", "index_sample"]


def _arg_reduce(op_type, input, axis, dtype, keepdims):
    x = input
    if axis is None:
        x = dispatch("reshape2", {"X": x}, {"shape": [-1]})
        axis = 0
    out = dispatch(op_type, {"X": x}, {"axis": int(axis)},
                   out_dtypes="int64", stop_gradient=True)
    if keepdims:
        ax = int(axis) % max(len(x.shape), 1)
        out = dispatch("unsqueeze2", {"X": out}, {"axes": [ax]},
                       out_dtypes="int64", stop_gradient=True)
    if dtype is not None and str(dtype) not in ("int64",):
        out = dispatch("cast", {"X": out}, {"out_dtype": str(dtype)},
                       out_dtypes=str(dtype))
    return out


def argmax(input, axis=None, dtype=None, out=None, keepdims=False,
           name=None):
    """search.py:45 — axis=None flattens first (reference flatten+axis 0)."""
    return _arg_reduce("arg_max", input, axis, dtype, keepdims)


def argmin(input, axis=None, dtype=None, out=None, keepdims=False,
           name=None):
    return _arg_reduce("arg_min", input, axis, dtype, keepdims)


def argsort(input, axis=-1, descending=False, name=None):
    out, idx = dispatch("argsort", {"X": input},
                        {"axis": int(axis), "descending": bool(descending)},
                        out_slots=("Out", "Indices"),
                        out_dtypes={"Out": None, "Indices": "int64"})
    return out, idx


def sort(input, axis=-1, descending=False, out=None, name=None):
    """search.py:289 — returns (sorted, indices)."""
    return argsort(input, axis=axis, descending=descending, name=name)


def topk(input, k, axis=-1, largest=True, sorted=True, name=None):
    """Largest/smallest k along ``axis``: non-last axes transpose to the
    back for the top_k op and back after; smallest-k negates in and out."""
    nd = len(input.shape)
    ax = int(axis) % nd if nd else 0
    x = input
    perm = None
    if nd and ax != nd - 1:
        perm = [i for i in range(nd) if i != ax] + [ax]
        x = dispatch("transpose2", {"X": x}, {"axis": perm})
    if not largest:
        x = dispatch("scale", {"X": x}, {"scale": -1.0})
    vals, idx = dispatch("top_k", {"X": x}, {"k": int(k)},
                         out_slots=("Out", "Indices"),
                         out_dtypes={"Out": None, "Indices": "int64"})
    if not largest:
        vals = dispatch("scale", {"X": vals}, {"scale": -1.0})
    if perm is not None:
        inv = [0] * nd
        for i, p in enumerate(perm):
            inv[p] = i
        vals = dispatch("transpose2", {"X": vals}, {"axis": inv})
        idx = dispatch("transpose2", {"X": idx}, {"axis": inv},
                       out_dtypes="int64", stop_gradient=True)
    return vals, idx


def where(condition, x, y, name=None):
    """search.py:381 — elementwise select."""
    return dispatch("where", {"Condition": condition, "X": x, "Y": y})


def index_select(input, index, dim=0):
    """search.py:138."""
    return dispatch("index_select", {"X": input, "Index": index},
                    {"dim": int(dim)})


def index_sample(x, index):
    """search.py:459 — per-row gather."""
    return dispatch("index_sample", {"X": x, "Index": index})


def nonzero(input, as_tuple=False):
    """search.py:202 — dynamic-shape host op (CPU utility on TPU)."""
    out = dispatch("where_index", {"Condition": input}, out_dtypes="int64",
                   stop_gradient=True)
    if not as_tuple:
        return out
    nd = len(input.shape)
    cols = [dispatch("slice", {"Input": out},
                     {"axes": [1], "starts": [i], "ends": [i + 1]})
            for i in range(nd)]
    return tuple(cols)


def has_inf(x):
    return dispatch("has_inf", {"X": x}, out_dtypes="bool",
                    stop_gradient=True)


def has_nan(x):
    return dispatch("has_nan", {"X": x}, out_dtypes="bool",
                    stop_gradient=True)
