"""paddle.tensor.search — parity with python/paddle/tensor/search.py
(argmax:45, index_select:138, nonzero:202, sort:289, where:381,
index_sample:459).
"""
from __future__ import annotations

import numpy as np

from ._dispatch import dispatch, in_dygraph_mode

__all__ = ["argmax", "argmin", "argsort", "has_inf", "has_nan", "topk",
           "where", "index_select", "nonzero", "sort", "index_sample"]


def argmax(input, axis=None, dtype=None, out=None, keepdims=False,
           name=None):
    """search.py:45 — axis=None flattens first (reference flatten+axis 0)."""
    x = input
    if axis is None:
        x = dispatch("reshape2", {"X": x}, {"shape": [-1]})
        axis = 0
    out = dispatch("arg_max", {"X": x}, {"axis": int(axis)},
                   out_dtypes="int64", stop_gradient=True)
    if dtype is not None and str(dtype) not in ("int64",):
        out = dispatch("cast", {"X": out}, {"out_dtype": str(dtype)},
                       out_dtypes=str(dtype))
    return out


def argmin(input, axis=None, dtype=None, out=None, keepdims=False,
           name=None):
    x = input
    if axis is None:
        x = dispatch("reshape2", {"X": x}, {"shape": [-1]})
        axis = 0
    out = dispatch("arg_min", {"X": x}, {"axis": int(axis)},
                   out_dtypes="int64", stop_gradient=True)
    if dtype is not None and str(dtype) not in ("int64",):
        out = dispatch("cast", {"X": out}, {"out_dtype": str(dtype)},
                       out_dtypes=str(dtype))
    return out


def argsort(input, axis=-1, descending=False, name=None):
    out, idx = dispatch("argsort", {"X": input},
                        {"axis": int(axis), "descending": bool(descending)},
                        out_slots=("Out", "Indices"),
                        out_dtypes={"Out": None, "Indices": "int64"})
    return out, idx


def sort(input, axis=-1, descending=False, out=None, name=None):
    """search.py:289 — returns (sorted, indices)."""
    return argsort(input, axis=axis, descending=descending, name=name)


def topk(input, k, axis=-1, largest=True, sorted=True, name=None):
    vals, idx = dispatch("top_k", {"X": input}, {"k": int(k)},
                         out_slots=("Out", "Indices"),
                         out_dtypes={"Out": None, "Indices": "int64"})
    return vals, idx


def where(condition, x, y, name=None):
    """search.py:381 — elementwise select."""
    return dispatch("where", {"Condition": condition, "X": x, "Y": y})


def index_select(input, index, dim=0):
    """search.py:138."""
    return dispatch("index_select", {"X": input, "Index": index},
                    {"dim": int(dim)})


def index_sample(x, index):
    """search.py:459 — per-row gather."""
    return dispatch("index_sample", {"X": x, "Index": index})


def nonzero(input, as_tuple=False):
    """search.py:202 — dynamic-shape host op (CPU utility on TPU)."""
    out = dispatch("where_index", {"Condition": input}, out_dtypes="int64",
                   stop_gradient=True)
    if not as_tuple:
        return out
    nd = len(input.shape)
    cols = [dispatch("slice", {"Input": out},
                     {"axes": [1], "starts": [i], "ends": [i + 1]})
            for i in range(nd)]
    return tuple(cols)


def has_inf(x):
    return dispatch("has_inf", {"X": x}, out_dtypes="bool",
                    stop_gradient=True)


def has_nan(x):
    return dispatch("has_nan", {"X": x}, out_dtypes="bool",
                    stop_gradient=True)
