"""paddle.tensor.random — parity with python/paddle/tensor/random.py
(randint:40, randn:209, randperm:320, rand:409, shuffle:~30).

Randomness lowers to jax.random with deterministic per-op keys (the
executor's rng stream in static mode, the eager stream in dygraph mode) —
the TPU-native replacement for the reference's curand states.
"""
from __future__ import annotations

from ._dispatch import dispatch

__all__ = ["shuffle", "randn", "rand", "randint", "randperm"]


def randint(low, high=None, shape=(1,), out=None, dtype=None, device=None,
            stop_gradient=False, seed=0, name=None):
    """random.py:40."""
    if high is None:
        low, high = 0, low
    return dispatch("randint", {},
                    {"shape": [int(s) for s in shape], "low": int(low),
                     "high": int(high), "dtype": str(dtype or "int64"),
                     "seed": int(seed)},
                    out_dtypes=str(dtype or "int64"),
                    stop_gradient=stop_gradient)


def randn(shape, out=None, dtype=None, device=None, stop_gradient=True,
          name=None):
    """random.py:209 — standard normal."""
    return dispatch("gaussian_random", {},
                    {"shape": [int(s) for s in shape], "mean": 0.0,
                     "std": 1.0, "dtype": str(dtype or "float32")},
                    out_dtypes=str(dtype or "float32"),
                    stop_gradient=stop_gradient)


def rand(shape, out=None, dtype=None, device=None, stop_gradient=True):
    """random.py:409 — U[0, 1)."""
    return dispatch("uniform_random", {},
                    {"shape": [int(s) for s in shape], "min": 0.0,
                     "max": 1.0, "dtype": str(dtype or "float32")},
                    out_dtypes=str(dtype or "float32"),
                    stop_gradient=stop_gradient)


def randperm(n, out=None, dtype="int64", device=None, stop_gradient=True,
             seed=0):
    """random.py:320."""
    return dispatch("randperm", {},
                    {"n": int(n), "dtype": str(dtype), "seed": int(seed)},
                    out_dtypes=str(dtype), stop_gradient=stop_gradient)


def shuffle(x, seed=None):
    """Permute along dim 0 (reference fluid.layers.shuffle alias):
    gather over a random permutation."""
    perm = randperm(x.shape[0], seed=int(seed or 0))
    return dispatch("index_select", {"X": x, "Index": perm}, {"dim": 0})
