"""paddle.tensor — the paddle-2.0-preview tensor namespace, parity with
python/paddle/tensor/__init__.py.  Every entry works in both dygraph and
static mode via the registry dispatch (_dispatch.py).
"""
from .attribute import rank, shape  # noqa: F401
from .creation import (  # noqa: F401
    arange, create_tensor, crop_tensor, diag, eye, fill_constant, full,
    full_like, linspace, meshgrid, ones, ones_like, tril, triu, zeros,
    zeros_like,
)
from .io import load, save  # noqa: F401
from .linalg import (  # noqa: F401
    bmm, cholesky, cross, dist, dot, histogram, matmul, norm, t, transpose,
)
from .logic import (  # noqa: F401
    allclose, elementwise_equal, equal, greater_equal, greater_than,
    is_empty, isfinite, less_equal, less_than, logical_and, logical_not,
    logical_or, logical_xor, not_equal, reduce_all, reduce_any,
)
from .manipulation import (  # noqa: F401
    cast, concat, expand, expand_as, flatten, flip, gather, gather_nd,
    reshape, reverse, roll, scatter, scatter_nd, scatter_nd_add,
    shard_index, slice, split, squeeze, stack, strided_slice, unbind,
    unique, unique_with_counts, unsqueeze, unstack,
)
from .math import (  # noqa: F401
    abs, acos, add, addcmul, addmm, asin, atan, ceil, clamp, cos, cumsum,
    div, elementwise_add, elementwise_div, elementwise_floordiv,
    elementwise_max, elementwise_min, elementwise_mod, elementwise_mul,
    elementwise_pow, elementwise_sub, elementwise_sum, erf, exp, floor,
    increment, inverse, kron, log, log1p, logsumexp, max, min, mm, mul,
    multiplex, pow, reciprocal, reduce_max, reduce_min, reduce_prod,
    reduce_sum, round, rsqrt, scale, sign, sin, sqrt, square, stanh, sum,
    sums, tanh, trace,
)
from .random import rand, randint, randn, randperm, shuffle  # noqa: F401
from .search import (  # noqa: F401
    argmax, argmin, argsort, has_inf, has_nan, index_sample, index_select,
    nonzero, sort, topk, where,
)
from .stat import mean, reduce_mean, std, var  # noqa: F401
