"""paddle.tensor.attribute — parity with python/paddle/tensor/attribute.py
(rank, shape aliases).
"""
from __future__ import annotations

from ._dispatch import dispatch, in_dygraph_mode

__all__ = ["rank", "shape"]


def shape(input):
    return dispatch("shape", {"Input": input}, out_dtypes="int32",
                    stop_gradient=True)


def rank(input):
    from .creation import fill_constant
    return fill_constant([1], "int32", len(input.shape))
