"""Dual-mode op dispatch powering the paddle-2.0-preview API surface.

In the reference, every 2.0 function branches on ``in_dygraph_mode()`` between
an eager ``core.ops`` kernel call and a ``LayerHelper.append_op`` graph build
(e.g. python/paddle/tensor/math.py:363 ``_elementwise_op_in_dygraph`` vs
``_elementwise_op``).  Here the op registry is the single source of truth:

- eager (dygraph) mode applies the op's XLA lowering directly to the values,
  taped for autograd via ``dygraph.varbase.apply_op`` — the TPU-native
  analogue of the reference's per-op eager kernel dispatch;
- static mode appends the op to the default Program; shape metadata comes
  from the registry's shape inference, and gradients from IR autodiff.

Both modes therefore execute the *same* lowering, so numerics match by
construction.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax

from ..dygraph import base as dygraph_base
from ..framework import unique_name
from ..framework.layer_helper import LayerHelper
from ..framework.registry import LowerCtx, _FakeOp, get_op_spec


def in_dygraph_mode() -> bool:
    return dygraph_base.enabled()


# deterministic eager-mode RNG stream; framework.random.manual_seed resets it
_EAGER_SEED = [0, 0]   # [seed, counter]


def reset_eager_seed(seed: int) -> None:
    _EAGER_SEED[0] = int(seed)
    _EAGER_SEED[1] = 0


def _next_eager_key():
    _EAGER_SEED[1] += 1
    return jax.random.fold_in(jax.random.PRNGKey(_EAGER_SEED[0]),
                              _EAGER_SEED[1])


def dispatch(op_type: str,
             inputs: Dict[str, Any],
             attrs: Optional[dict] = None,
             out_slots: Sequence[str] = ("Out",),
             out_dtypes: Any = None,
             out_counts: Optional[Dict[str, int]] = None,
             stop_gradient: bool = False):
    """Run/append one registered op; returns one value per out slot.

    ``inputs`` values may be a single tensor or a list (multi-var slots);
    ``None`` slots are dropped.  A slot listed in ``out_counts`` with n > 1
    yields a list of n outputs (static mode needs the count up front; eager
    mode returns however many the lowering produced).
    """
    attrs = dict(attrs or {})
    ins = {k: (list(v) if isinstance(v, (list, tuple)) else [v])
           for k, v in inputs.items() if v is not None}
    if in_dygraph_mode():
        return _dispatch_eager(op_type, ins, attrs, tuple(out_slots))
    return _dispatch_static(op_type, ins, attrs, tuple(out_slots),
                            out_dtypes, out_counts or {}, stop_gradient)


def _dispatch_eager(op_type, ins, attrs, out_slots):
    from ..dygraph.varbase import apply_op

    spec = get_op_spec(op_type)
    layout = [(slot, len(vals)) for slot, vals in ins.items()]
    flat = [v for vals in ins.values() for v in vals]
    in_names = {s: [f"__eager_{s}_{i}" for i in range(n)] for s, n in layout}
    # output names must be DETERMINISTIC under the eager seed counter, not
    # unique_name: ctx.rng_for salts the key from them, so manual_seed(n)
    # must reproduce both the key and the names to replay the random stream
    rng_key = _next_eager_key()
    out_names = {s: [f"__eager.{op_type}.{s}.{_EAGER_SEED[1]}"]
                 for s in out_slots}
    fake = _FakeOp(op_type, in_names, out_names, attrs, None)

    def fn(*vals):
        it = iter(vals)
        ins_v = {slot: [next(it) for _ in range(n)] for slot, n in layout}
        ctx = LowerCtx(None, None, {}, rng_key=rng_key)
        outs = spec.lower(ctx, fake, ins_v)
        res = []
        for s in out_slots:
            v = outs.get(s)
            if isinstance(v, (list, tuple)) and len(v) == 1:
                v = v[0]
            res.append(v)
        return tuple(res) if len(res) > 1 else res[0]

    return apply_op(fn, *flat)


def _dispatch_static(op_type, ins, attrs, out_slots, out_dtypes, out_counts,
                     stop_gradient):
    helper = LayerHelper(op_type)
    first = next((v for vals in ins.values() for v in vals
                  if hasattr(v, "dtype")), None)
    outs, ret = {}, []
    for s in out_slots:
        dt = out_dtypes.get(s) if isinstance(out_dtypes, dict) else out_dtypes
        dt = dt or (first.dtype if first is not None else "float32")
        n = out_counts.get(s, 1)
        vs = [helper.create_variable_for_type_inference(
            dt, stop_gradient=stop_gradient) for _ in range(n)]
        outs[s] = vs
        ret.append(vs if n > 1 else vs[0])
    helper.append_op(type=op_type, inputs=ins, outputs=outs, attrs=attrs)
    return tuple(ret) if len(ret) > 1 else ret[0]
