"""paddle.tensor.io — parity with python/paddle/tensor/io.py (aliases of
fluid save/load)."""
from ..io import save, load  # noqa: F401

__all__ = ["save", "load"]
