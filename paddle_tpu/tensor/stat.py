"""paddle.tensor.stat — parity with python/paddle/tensor/stat.py
(var:29, std:108).
"""
from __future__ import annotations

from ._dispatch import dispatch
from .math import _reduce, reduce_sum, square, sqrt, scale

__all__ = ["mean", "reduce_mean", "std", "var"]


def mean(x, name=None):
    return dispatch("mean", {"X": x})


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim)


def var(input, axis=None, keepdim=False, unbiased=True, out=None, name=None):
    """stat.py:29 — E[(x - E[x])^2], Bessel-corrected when unbiased."""
    import numpy as np

    m = _reduce("reduce_mean", input, axis, True)
    diff = dispatch("elementwise_sub", {"X": input, "Y": m}, {"axis": -1})
    v = _reduce("reduce_mean", square(diff), axis, keepdim)
    if unbiased:
        shape = input.shape
        if axis is None:
            n = int(np.prod(shape))
        else:
            dims = [axis] if isinstance(axis, int) else list(axis)
            n = int(np.prod([shape[d] for d in dims]))
        if n > 1:
            v = scale(v, scale=n / (n - 1))
    return v


def std(input, axis=None, keepdim=False, unbiased=True, out=None, name=None):
    """stat.py:108."""
    return sqrt(var(input, axis=axis, keepdim=keepdim, unbiased=unbiased))
