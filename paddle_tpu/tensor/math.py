"""paddle.tensor.math — parity with python/paddle/tensor/math.py
(add:412, div:557, mm:913, addmm:1018, logsumexp:1087, inverse:1158,
max:1233, min:1313, addcmul:1438, clamp:1487, trace:1575, kron:1672).

Unary/elementwise entries run the registered op lowerings in both modes.
"""
from __future__ import annotations

import numpy as np

from ._dispatch import dispatch

__all__ = [
    "abs", "acos", "asin", "atan", "ceil", "cos", "cumsum",
    "elementwise_add", "elementwise_div", "elementwise_floordiv",
    "elementwise_max", "elementwise_min", "elementwise_mod",
    "elementwise_mul", "elementwise_pow", "elementwise_sub", "exp", "floor",
    "increment", "log", "mul", "multiplex", "pow", "reciprocal",
    "reduce_max", "reduce_min", "reduce_prod", "reduce_sum", "round",
    "rsqrt", "scale", "sign", "sin", "sqrt", "square", "stanh", "sum",
    "sums", "tanh", "elementwise_sum", "max", "min", "mm", "div", "add",
    "logsumexp", "inverse", "log1p", "erf", "addcmul", "addmm", "clamp",
    "trace", "kron",
]


def _unary(op_type):
    def fn(x, out=None, name=None):
        return dispatch(op_type, {"X": x})
    fn.__name__ = op_type
    fn.__doc__ = f"paddle.{op_type} — elementwise {op_type} (2.0 alias)."
    return fn


abs = _unary("abs")
acos = _unary("acos")
asin = _unary("asin")
atan = _unary("atan")
ceil = _unary("ceil")
cos = _unary("cos")
exp = _unary("exp")
floor = _unary("floor")
log = _unary("log")
reciprocal = _unary("reciprocal")
round = _unary("round")
rsqrt = _unary("rsqrt")
sign = _unary("sign")
sin = _unary("sin")
sqrt = _unary("sqrt")
square = _unary("square")
tanh = _unary("tanh")
log1p = _unary("log1p")
erf = _unary("erf")


def stanh(x, scale_a=0.67, scale_b=1.7159, out=None, name=None):
    return dispatch("stanh", {"X": x},
                    {"scale_a": scale_a, "scale_b": scale_b})


def _binary(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        out = dispatch(op_type, {"X": x, "Y": y}, {"axis": int(axis)})
        if act:
            out = dispatch(act, {"X": out})
        return out
    fn.__name__ = op_type
    fn.__doc__ = f"paddle.{op_type} (2.0 alias of the fluid elementwise op)."
    return fn


elementwise_add = _binary("elementwise_add")
elementwise_div = _binary("elementwise_div")
elementwise_floordiv = _binary("elementwise_floordiv")
elementwise_max = _binary("elementwise_max")
elementwise_min = _binary("elementwise_min")
elementwise_mod = _binary("elementwise_mod")
elementwise_mul = _binary("elementwise_mul")
elementwise_pow = _binary("elementwise_pow")
elementwise_sub = _binary("elementwise_sub")


def add(x, y, alpha=1, out=None, name=None):
    """math.py:412 — out = x + alpha*y (alpha folds into a scale)."""
    if alpha != 1:
        y = scale(y, scale=alpha)
    return dispatch("elementwise_add", {"X": x, "Y": y}, {"axis": -1})


def div(x, y, out=None, name=None):
    """math.py:557."""
    return dispatch("elementwise_div", {"X": x, "Y": y}, {"axis": -1})


def pow(input, exponent, out=None, name=None):
    """math.py:192 — exponent may be a python scalar or a tensor."""
    if hasattr(exponent, "dtype") and not np.isscalar(exponent):
        return dispatch("pow", {"X": input, "FactorTensor": exponent}, {})
    return dispatch("pow", {"X": input}, {"factor": float(exponent)})


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, out=None, name=None):
    """math.py:263 — the fluid `mul` matmul with flattening dims."""
    return dispatch("mul", {"X": x, "Y": y},
                    {"x_num_col_dims": int(x_num_col_dims),
                     "y_num_col_dims": int(y_num_col_dims)})


def mm(input, mat2, out=None, name=None):
    """math.py:913 — matrix multiply, no broadcast-flattening."""
    return dispatch("matmul", {"X": input, "Y": mat2},
                    {"transpose_X": False, "transpose_Y": False})


def addmm(input, x, y, alpha=1.0, beta=1.0, name=None):
    """math.py:1018 — out = alpha*x@y + beta*input."""
    return dispatch("addmm", {"Input": input, "X": x, "Y": y},
                    {"Alpha": float(alpha), "Beta": float(beta)})


def addcmul(input, tensor1, tensor2, value=1.0, out=None, name=None):
    """math.py:1438 — input + value * tensor1 * tensor2."""
    prod = dispatch("elementwise_mul", {"X": tensor1, "Y": tensor2},
                    {"axis": -1})
    if value != 1.0:
        prod = scale(prod, scale=value)
    return dispatch("elementwise_add", {"X": input, "Y": prod}, {"axis": -1})


def clamp(input, min=None, max=None, output=None, name=None):
    """math.py:1487 — clip to [min, max]."""
    lo = float("-inf") if min is None else float(min)
    hi = float("inf") if max is None else float(max)
    return dispatch("clip", {"X": input}, {"min": lo, "max": hi})


def trace(input, offset=0, dim1=0, dim2=1, out=None, name=None):
    """math.py:1575."""
    return dispatch("trace", {"Input": input},
                    {"offset": int(offset), "axis1": int(dim1),
                     "axis2": int(dim2)})


def kron(x, y, out=None, name=None):
    """math.py:1672 — Kronecker product."""
    return dispatch("kron", {"X": x, "Y": y})


def inverse(input, out=None, name=None):
    """math.py:1158 — batched matrix inverse."""
    return dispatch("inverse", {"Input": input})


def logsumexp(x, dim=None, keepdim=False, out=None, name=None):
    """math.py:1087 — log(sum(exp(x))) over dims, numerically stable.

    Composed from exp/sum/log ops after max-shift; the fused XLA graph is
    a single stable reduction.
    """
    m = _reduce("reduce_max", x, dim, True)
    shifted = dispatch("elementwise_sub", {"X": x, "Y": m}, {"axis": -1})
    s = _reduce("reduce_sum", dispatch("exp", {"X": shifted}), dim, keepdim)
    if keepdim:
        mk = m
    else:
        # squeeze the kept dims of the max already computed (a second
        # reduce_max over x would be a full extra reduction)
        nd = len(x.shape)
        dims = list(range(nd)) if dim is None else \
            [d % nd for d in ([dim] if isinstance(dim, int) else list(dim))]
        mk = dispatch("squeeze2", {"X": m}, {"axes": dims})
    return dispatch("elementwise_add",
                    {"X": dispatch("log", {"X": s}), "Y": mk}, {"axis": -1})


def _reduce(op_type, x, dim, keep_dim):
    if dim is None:
        attrs = {"dim": [], "keep_dim": keep_dim, "reduce_all": True}
    else:
        dims = [dim] if isinstance(dim, int) else list(dim)
        attrs = {"dim": dims, "keep_dim": keep_dim}
    return dispatch(op_type, {"X": x}, attrs)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim)


def max(input, dim=None, keep_dim=False, out=None, name=None):
    """math.py:1233 — reduce max with torch-style dim arg."""
    return _reduce("reduce_max", input, dim, keep_dim)


def min(input, dim=None, keep_dim=False, out=None, name=None):
    """math.py:1313."""
    return _reduce("reduce_min", input, dim, keep_dim)


def sum(input, dim=None, dtype=None, keep_dim=False, name=None):
    """math.py:710 — reduce sum (optionally casting first)."""
    if dtype is not None:
        input = dispatch("cast", {"X": input}, {"out_dtype": str(dtype)},
                         out_dtypes=str(dtype))
    return _reduce("reduce_sum", input, dim, keep_dim)


def elementwise_sum(inputs, name=None):
    """math.py:815 — add a list of tensors (the fluid `sum` op)."""
    return dispatch("sum", {"X": list(inputs)})


def sums(input, out=None):
    return dispatch("sum", {"X": list(input)})


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = dispatch("scale", {"X": x},
                   {"scale": float(scale), "bias": float(bias),
                    "bias_after_scale": bool(bias_after_scale)})
    if act:
        out = dispatch(act, {"X": out})
    return out


def increment(x, value=1.0, in_place=True):
    return dispatch("increment", {"X": x}, {"step": float(value)})


def multiplex(inputs, index):
    return dispatch("multiplex", {"X": list(inputs), "Ids": index})


def cumsum(x, axis=None, exclusive=False, reverse=False, name=None):
    attrs = {"exclusive": bool(exclusive), "reverse": bool(reverse)}
    if axis is None:
        attrs["flatten"] = True
        attrs["axis"] = -1
    else:
        attrs["axis"] = int(axis)
    return dispatch("cumsum", {"X": x}, attrs)
