"""paddle.tensor.linalg — parity with python/paddle/tensor/linalg.py
(matmul:38, norm:174, dist:352, dot:453, t:512, cross:586, cholesky:651,
bmm:707, histogram:757).
"""
from __future__ import annotations

from ._dispatch import dispatch

__all__ = ["matmul", "dot", "norm", "transpose", "dist", "t", "cross",
           "cholesky", "bmm", "histogram"]


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    """linalg.py:38."""
    return dispatch("matmul", {"X": x, "Y": y},
                    {"transpose_X": bool(transpose_x),
                     "transpose_Y": bool(transpose_y),
                     "alpha": float(alpha)})


def dot(x, y, name=None):
    """linalg.py:453 — 1-D/2-D row-wise dot product."""
    return dispatch("dot", {"X": x, "Y": y})


def bmm(x, y, name=None):
    """linalg.py:707 — batched matmul [b,m,k]@[b,k,n]."""
    return dispatch("bmm", {"X": x, "Y": y})


def t(input, name=None):
    """linalg.py:512 — transpose of a 0/1/2-D tensor."""
    nd = len(input.shape)
    if nd < 2:
        return dispatch("assign", {"X": input})
    return dispatch("transpose2", {"X": input}, {"axis": [1, 0]})


def transpose(x, perm, name=None):
    return dispatch("transpose2", {"X": x}, {"axis": list(perm)})


def dist(x, y, p=2):
    """linalg.py:352 — p-norm of x - y."""
    return dispatch("dist", {"X": x, "Y": y}, {"p": float(p)})


def cross(input, other, dim=None):
    """linalg.py:586."""
    attrs = {} if dim is None else {"dim": int(dim)}
    return dispatch("cross", {"X": input, "Y": other}, attrs)


def cholesky(x, upper=False):
    """linalg.py:651."""
    return dispatch("cholesky", {"X": x}, {"upper": bool(upper)})


def histogram(input, bins=100, min=0, max=0):
    """linalg.py:757 — int64 bin counts."""
    return dispatch("histogram", {"X": input},
                    {"bins": int(bins), "min": min, "max": max},
                    out_dtypes="int64", stop_gradient=True)


def norm(input, p="fro", axis=None, keepdim=False, out=None, name=None):
    """linalg.py:174 — frobenius_norm or p_norm depending on p."""
    if p == "fro":
        if axis is None:
            attrs = {"dim": [], "keep_dim": keepdim, "reduce_all": True}
        else:
            dims = [axis] if isinstance(axis, int) else list(axis)
            attrs = {"dim": dims, "keep_dim": keepdim}
        return dispatch("frobenius_norm", {"X": input}, attrs)
    ax = axis if isinstance(axis, int) else (axis[0] if axis else -1)
    return dispatch("p_norm", {"X": input},
                    {"porder": float(p), "axis": int(ax),
                     "keepdim": bool(keepdim)})
