"""paddle.tensor.logic — parity with python/paddle/tensor/logic.py
(equal:55 — reduce-all semantics at 2.0-alpha, allclose:126,
elementwise_equal:211).
"""
from __future__ import annotations

from ._dispatch import dispatch

__all__ = [
    "equal", "greater_equal", "greater_than", "is_empty", "isfinite",
    "less_equal", "less_than", "logical_and", "logical_not", "logical_or",
    "logical_xor", "not_equal", "reduce_all", "reduce_any", "allclose",
    "elementwise_equal",
]


def _cmp(op_type):
    def fn(x, y, cond=None, name=None):
        return dispatch(op_type, {"X": x, "Y": y}, out_dtypes="bool")
    fn.__name__ = op_type
    fn.__doc__ = f"paddle.{op_type} — elementwise comparison (2.0 alias)."
    return fn


greater_equal = _cmp("greater_equal")
greater_than = _cmp("greater_than")
less_equal = _cmp("less_equal")
less_than = _cmp("less_than")
not_equal = _cmp("not_equal")
elementwise_equal = _cmp("equal")


def equal(x, y, axis=-1, name=None):
    """logic.py:55 — 2.0-alpha `equal` reduces to ONE bool: True iff all
    elements equal (the fluid elementwise op is `elementwise_equal` here).
    Composed as equal -> reduce_all; XLA fuses the pair."""
    ew = dispatch("equal", {"X": x, "Y": y}, {"axis": int(axis)},
                  out_dtypes="bool")
    return dispatch("reduce_all", {"X": ew},
                    {"dim": [], "keep_dim": False, "reduce_all": True},
                    out_dtypes="bool")


def _logical(op_type, unary=False):
    if unary:
        def fn(x, out=None, name=None):
            return dispatch(op_type, {"X": x}, out_dtypes="bool")
    else:
        def fn(x, y, out=None, name=None):
            return dispatch(op_type, {"X": x, "Y": y}, out_dtypes="bool")
    fn.__name__ = op_type
    return fn


logical_and = _logical("logical_and")
logical_or = _logical("logical_or")
logical_xor = _logical("logical_xor")
logical_not = _logical("logical_not", unary=True)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    attrs = ({"dim": [], "keep_dim": keep_dim, "reduce_all": True}
             if dim is None else
             {"dim": [dim] if isinstance(dim, int) else list(dim),
              "keep_dim": keep_dim})
    return dispatch("reduce_all", {"X": input}, attrs, out_dtypes="bool")


def reduce_any(input, dim=None, keep_dim=False, name=None):
    attrs = ({"dim": [], "keep_dim": keep_dim, "reduce_all": True}
             if dim is None else
             {"dim": [dim] if isinstance(dim, int) else list(dim),
              "keep_dim": keep_dim})
    return dispatch("reduce_any", {"X": input}, attrs, out_dtypes="bool")


def allclose(input, other, rtol=1e-05, atol=1e-08, equal_nan=False,
             name=None):
    """logic.py:126."""
    return dispatch("allclose", {"Input": input, "Other": other},
                    {"rtol": float(rtol), "atol": float(atol),
                     "equal_nan": bool(equal_nan)}, out_dtypes="bool",
                    stop_gradient=True)


def is_empty(x, cond=None):
    return dispatch("is_empty", {"X": x}, out_dtypes="bool",
                    stop_gradient=True)


def isfinite(x):
    return dispatch("isfinite", {"X": x}, out_dtypes="bool",
                    stop_gradient=True)
