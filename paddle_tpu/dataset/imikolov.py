"""paddle.dataset.imikolov — parity with python/paddle/dataset/imikolov.py
(build_dict; train/test(word_idx, n) yield n-gram tuples — imikolov.py:100;
DataType.SEQ yields (src_seq, trg_seq) — :107)."""
from __future__ import annotations

from .common import fixture_rng

__all__ = ["build_dict", "train", "test", "DataType"]

_VOCAB = 2073            # reference imikolov dict size ballpark
TRAIN_SENTENCES = 512
TEST_SENTENCES = 128


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    d = {f"w{i}": i for i in range(_VOCAB)}
    d["<unk>"] = len(d)
    d["<s>"] = len(d)
    d["<e>"] = len(d)
    return d


def _creator(split, sentences, word_idx, n, data_type):
    def reader():
        rs = fixture_rng("imikolov", split)
        s_id, e_id = word_idx["<s>"], word_idx["<e>"]
        vocab = min(len(word_idx), _VOCAB)
        for _ in range(sentences):
            ln = int(rs.randint(5, 20))
            l = [s_id] + [int(t) for t in rs.randint(0, vocab, ln)] + [e_id]
            if data_type == DataType.NGRAM:
                if len(l) >= n:
                    l = l[:]
                    for i in range(n, len(l) + 1):
                        yield tuple(l[i - n:i])     # imikolov.py:100
            else:
                yield l[:-1], l[1:]                 # imikolov.py:107

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _creator("train", TRAIN_SENTENCES, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _creator("test", TEST_SENTENCES, word_idx, n, data_type)
