"""paddle.dataset.cifar — parity with python/paddle/dataset/cifar.py
(reader yields (float32[3072] in [0,1], int label); train10/test10 and
train100/test100)."""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train10", "test10", "train100", "test100"]

TRAIN_SIZE = 1024
TEST_SIZE = 256


def _creator(split, n, num_classes):
    def reader():
        rs = fixture_rng(f"cifar{num_classes}", split)
        labels = rs.randint(0, num_classes, n)
        for i in range(n):
            base = np.full(3072, (labels[i] + 0.5) / num_classes,
                           np.float32)
            img = np.clip(base + rs.rand(3072).astype(np.float32) * 0.3,
                          0, 1)
            yield img, int(labels[i])            # cifar.py:55

    return reader


def train10():
    return _creator("train", TRAIN_SIZE, 10)


def test10():
    return _creator("test", TEST_SIZE, 10)


def train100():
    return _creator("train", TRAIN_SIZE, 100)


def test100():
    return _creator("test", TEST_SIZE, 100)
