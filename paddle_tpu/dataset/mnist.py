"""paddle.dataset.mnist — parity with python/paddle/dataset/mnist.py
(reader_creator:41 — yields (image[784] float32 in [-1, 1], int label)).

Deterministic local fixture (common.py): blob-per-digit images so a small
model genuinely learns; same shapes/normalization as the reference's
idx-file reader.
"""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train", "test"]

TRAIN_SIZE = 2048
TEST_SIZE = 512


def _make(split, n):
    rs = fixture_rng("mnist", split)
    labels = rs.randint(0, 10, n).astype(np.int64)
    images = np.empty((n, 784), np.float32)
    grid = np.stack(np.meshgrid(np.arange(28), np.arange(28),
                                indexing="ij"), -1).reshape(-1, 2)
    for i, lbl in enumerate(labels):
        # one gaussian blob per class at a class-specific center
        cy, cx = 6 + (lbl % 5) * 4, 6 + (lbl // 5) * 14
        d2 = ((grid[:, 0] - cy) ** 2 + (grid[:, 1] - cx) ** 2)
        img = np.exp(-d2 / 18.0) + rs.rand(784) * 0.15
        images[i] = np.clip(img, 0, 1) * 2.0 - 1.0   # reference: [-1, 1]
    return images, labels


def reader_creator(split, n):
    def reader():
        images, labels = _make(split, n)
        for i in range(n):
            yield images[i, :], int(labels[i])

    return reader


def train():
    """mnist.py:92 train reader creator — (float32[784] in [-1,1], int)."""
    return reader_creator("train", TRAIN_SIZE)


def test():
    return reader_creator("test", TEST_SIZE)
