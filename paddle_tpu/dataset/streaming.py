"""Fault-tolerant sharded streaming data engine (ISSUE 11, docs/data.md).

The reference Paddle's production identity is its multi-threaded
DataFeed/Dataset pipeline (framework/data_feed.cc, data_set.cc); this module
is its TPU-native, fault-tolerant superset for long training runs where the
INPUT path — not the step — is the most common fault source:

- **Sharded streams**: a file list is ordered deterministically per epoch
  (optionally shuffled from the StreamState rng seed) and assigned
  round-robin across hosts (:func:`assign_shards`, the generalization of
  ``dataset.common.cluster_files_reader``).  An empty assignment is a hard
  error, never a silent empty stream.
- **Retry with backoff**: every shard open and mid-shard read goes through
  :class:`RetryPolicy` — bounded exponential backoff with jitter and a
  per-shard attempt budget, metered as
  ``paddle_input_retries_total{stage=open|read}``.  A shard that exhausts
  its budget raises :class:`ShardReadError` naming the shard.
- **Corrupt-record quarantine**: records whose ``decode_fn``/``validate_fn``
  raises are appended to a JSONL sidecar (shard, record index, error, raw
  prefix) and skipped under a bounded per-shard ``skip_budget``; exceeding
  the budget raises :class:`QuarantineOverflowError` naming the shard —
  fail fast instead of training on a rotten shard.
- **Worker watchdog**: decode runs on a small worker pool; a worker stuck
  past ``watchdog_deadline_s`` on one record is abandoned (daemon thread)
  and replaced, and its record is re-dispatched —
  ``paddle_input_worker_recycles_total`` — so one wedged tokenizer call
  never stalls the gang.
- **Graceful stall degradation**: the consumer waits in bounded ticks; the
  wait is charged to the goodput ledger's ``input_stall`` category, and a
  sustained stall logs a supervisor-visible warning naming the slowest
  shard plus an ``input_stall.rank<R>.json`` report into the shared health
  dir (``PADDLE_HEALTH_DIR``) that ``parallel.launch`` surfaces.
- **Deterministic resume**: :class:`StreamState` (shard-list hash, per-shard
  raw-record offsets, epoch, rng seed) snapshots at every batch boundary
  and serializes into ``ElasticCheckpointer``'s ``data_state``.  Restoring
  the state resumes the stream bit-exactly on the same host count; on a
  *changed* host count, :meth:`StreamState.merge` of the per-host states
  reassigns shards and resumes each from its recorded offset — per-shard
  record order is always total and preserved, and every record of the
  epoch is consumed exactly once (the documented global-order guarantee;
  cross-shard interleaving is the only thing that may change).

Determinism note: retries, quarantine skips and worker recycles never
change WHICH records a batch contains or their order — only wall-clock.
The decoded-record stream is a pure function of (shard bytes, shard order,
offsets), which is what makes SIGKILL-resume bit-exact
(tools/fault_bench.py stream scenarios).
"""
from __future__ import annotations

import json
import logging
import os
import queue as _queue
import random as _random
import tempfile
import threading
import time
import zlib
from typing import (Any, Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple)

import numpy as np

from ..observability import goodput as _goodput
from ..observability import metrics as _obs_metrics

__all__ = [
    "StreamError", "ShardReadError", "QuarantineOverflowError",
    "Shard", "make_shards", "shard_list_hash", "assign_shards",
    "RetryPolicy", "StreamConfig", "StreamState", "ShardedStream",
    "StreamingDataset",
]

logger = logging.getLogger("paddle_tpu.streaming")

_gp = _goodput.ledger()
_REG = _obs_metrics.default_registry()
_m_retries = _REG.counter(
    "paddle_input_retries_total",
    "Input-path retries by stage (shard open / mid-shard read)",
    ("stage",))
_m_quarantined = _REG.counter(
    "paddle_input_records_quarantined_total",
    "Records quarantined to the JSONL sidecar (decode/validate failures)")
_m_recycles = _REG.counter(
    "paddle_input_worker_recycles_total",
    "Stuck decode workers abandoned and replaced by the input watchdog")
_m_stall_s = _REG.counter(
    "paddle_input_stall_seconds_total",
    "Wall seconds the stream consumer waited on the decode pipeline")
_m_records = _REG.counter(
    "paddle_input_records_total", "Records emitted by sharded streams")
_m_batches = _REG.counter(
    "paddle_input_batches_total", "Batches emitted by sharded streams")
# shard label cardinality is bounded by the registry's series cap: runs
# with more shards than the cap collapse the excess into one
# "<other>" series instead of growing the exposition without bound
_g_progress = _REG.gauge(
    "paddle_input_shard_progress",
    "Raw records consumed per shard (resume offset)", ("shard",),
    max_series=512)


def quarantined_total() -> float:
    """Process-wide quarantined-record count (monitor rows carry this)."""
    return _m_quarantined.value


class StreamError(RuntimeError):
    pass


class ShardReadError(StreamError):
    """A shard open/read exhausted its retry budget (names the shard)."""


class QuarantineOverflowError(StreamError):
    """A shard's corrupt-record count exceeded the skip budget (names the
    shard) — the stream fails fast instead of silently training on noise."""


# ---------------------------------------------------------------------------
# Shards + assignment
# ---------------------------------------------------------------------------

class Shard:
    """One input file: a stable ``name`` (the resume key), path, size."""

    __slots__ = ("name", "path", "size")

    def __init__(self, name: str, path: str, size: int):
        self.name = name
        self.path = path
        self.size = int(size)

    def __repr__(self):
        return f"Shard({self.name!r}, {self.size}B)"


def make_shards(paths: Sequence[str]) -> List[Shard]:
    """Paths -> Shard list.  Names are basenames when unique (so a stream
    survives the data directory moving), full paths otherwise."""
    paths = [str(p) for p in paths]
    if not paths:
        raise StreamError("stream has no shards (empty file list)")
    bases = [os.path.basename(p) for p in paths]
    unique = len(set(bases)) == len(bases)
    out = []
    for p, b in zip(paths, bases):
        try:
            size = os.path.getsize(p)
        except OSError:
            size = -1   # unreadable now; the open retry path will report it
        out.append(Shard(b if unique else p, p, size))
    return out


def shard_list_hash(shards: Sequence[Shard]) -> int:
    """Identity of the shard SET (names + sizes, order-independent): a
    StreamState only resumes a stream over the same bytes."""
    h = 0
    for s in sorted(shards, key=lambda s: s.name):
        h = zlib.crc32(f"{s.name}:{s.size}\n".encode(), h)
    return h & 0xFFFFFFFF


def epoch_shard_order(shards: Sequence[Shard], seed: int, epoch: int,
                      shuffle: bool = False) -> List[Shard]:
    """Deterministic global shard order for one epoch — identical on every
    host (assignment slices it), derived only from (seed, epoch)."""
    out = sorted(shards, key=lambda s: s.name)
    if shuffle:
        _random.Random((int(seed) << 20) ^ int(epoch)).shuffle(out)
    return out


def assign_shards(ordered: Sequence[Shard], host_id: int,
                  num_hosts: int) -> List[Shard]:
    """Round-robin host assignment over the epoch order.  A host with no
    shards is a configuration error (the "loss never moves" footgun), not
    an empty stream."""
    if num_hosts < 1 or not (0 <= host_id < num_hosts):
        raise StreamError(
            f"bad host assignment: host_id={host_id} num_hosts={num_hosts}")
    mine = list(ordered[host_id::num_hosts])
    if not mine:
        raise StreamError(
            f"host {host_id}/{num_hosts} is assigned no shards "
            f"({len(ordered)} shard(s) total) — fewer shards than hosts; "
            "reduce the host count or split the input files")
    return mine


# ---------------------------------------------------------------------------
# Retry policy
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Bounded exponential backoff with jitter for shard I/O.

    ``max_attempts`` is the per-shard attempt budget per stage; jitter
    de-synchronizes a gang hammering a recovering filesystem.  Sleeping is
    injectable for tests."""

    def __init__(self, max_attempts: int = 5, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, jitter: float = 0.5,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.sleep = sleep

    def delay(self, attempt: int) -> float:
        """Backoff before attempt N+1 (attempts are 1-based)."""
        d = min(self.base_delay_s * (2 ** (attempt - 1)), self.max_delay_s)
        return d * (1.0 + self.jitter * _random.random())


# ---------------------------------------------------------------------------
# StreamState: the deterministic-resume token
# ---------------------------------------------------------------------------

STATE_VERSION = 1


class StreamState:
    """Serializable resume position of a sharded stream.

    ``offsets[name]`` counts RAW records (file lines) consumed from that
    shard — quarantined records included, so a resume skips them without
    re-quarantining side effects changing batch composition.  Snapshots
    are taken at batch boundaries only: a record is "consumed" once the
    batch containing it has been yielded to the training loop.
    """

    def __init__(self, shard_hash: int, epoch: int = 0,
                 offsets: Optional[Dict[str, int]] = None, seed: int = 0,
                 records: int = 0):
        self.shard_hash = int(shard_hash)
        self.epoch = int(epoch)
        self.offsets: Dict[str, int] = dict(offsets or {})
        self.seed = int(seed)
        self.records = int(records)

    def to_dict(self) -> Dict[str, Any]:
        return {"version": STATE_VERSION, "shard_hash": self.shard_hash,
                "epoch": self.epoch, "offsets": dict(self.offsets),
                "seed": self.seed, "records": self.records}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StreamState":
        ver = int(d.get("version", 1))
        if ver > STATE_VERSION:
            raise StreamError(
                f"stream state version {ver} is newer than this runtime "
                f"({STATE_VERSION})")
        return cls(shard_hash=int(d["shard_hash"]),
                   epoch=int(d.get("epoch", 0)),
                   offsets={str(k): int(v)
                            for k, v in (d.get("offsets") or {}).items()},
                   seed=int(d.get("seed", 0)),
                   records=int(d.get("records", 0)))

    @classmethod
    def merge(cls, states: Sequence["StreamState"]) -> "StreamState":
        """Merge per-host states for a host-count change: per-shard offsets
        union (each shard is owned by exactly one host, so keys are
        disjoint).  All states must describe the same shard set and epoch.
        """
        if not states:
            raise StreamError("cannot merge zero stream states")
        first = states[0]
        out = cls(first.shard_hash, first.epoch, {}, first.seed, 0)
        for st in states:
            if st.shard_hash != out.shard_hash:
                raise StreamError(
                    "cannot merge stream states over different shard sets "
                    f"({st.shard_hash:#x} vs {out.shard_hash:#x})")
            if st.epoch != out.epoch:
                raise StreamError(
                    "cannot merge stream states at different epochs "
                    f"({st.epoch} vs {out.epoch}) — checkpoint the gang at "
                    "one barrier")
            for k, v in st.offsets.items():
                out.offsets[k] = max(int(v), out.offsets.get(k, 0))
            out.records += st.records
        return out


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------

class StreamConfig:
    def __init__(self, batch_size: int = 1, drop_last: bool = False,
                 num_workers: int = 2, prefetch: int = 8,
                 retry: Optional[RetryPolicy] = None,
                 skip_budget: int = 16,
                 quarantine_path: Optional[str] = None,
                 watchdog_deadline_s: float = 30.0,
                 stall_warn_s: float = 5.0,
                 shuffle_shards: bool = False, seed: int = 0,
                 validate_fn: Optional[Callable[[Any], None]] = None,
                 charge_goodput: bool = True):
        self.batch_size = max(1, int(batch_size))
        self.drop_last = bool(drop_last)
        self.num_workers = max(1, int(num_workers))
        self.prefetch = max(2, int(prefetch))
        self.retry = retry or RetryPolicy()
        self.skip_budget = int(skip_budget)
        self.quarantine_path = quarantine_path
        self.watchdog_deadline_s = float(watchdog_deadline_s)
        self.stall_warn_s = float(stall_warn_s)
        self.shuffle_shards = bool(shuffle_shards)
        self.seed = int(seed)
        self.validate_fn = validate_fn
        # the executor's prefetch_to_device already attributes consumer
        # waits to the goodput ledger; direct consumers keep this True so
        # stalls are attributed exactly once either way
        self.charge_goodput = bool(charge_goodput)


def _default_quarantine_path() -> str:
    d = os.environ.get("PADDLE_INPUT_QUARANTINE_DIR") or \
        os.path.join(tempfile.gettempdir(), "paddle_tpu_quarantine")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"quarantine.{os.getpid()}.jsonl")


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = ("thread", "current", "busy_since", "abandoned", "idx")

    def __init__(self, idx: int):
        self.idx = idx
        self.thread: Optional[threading.Thread] = None
        self.current = None          # (seq, shard_name, raw_idx, raw)
        self.busy_since = 0.0
        self.abandoned = False


class ShardedStream:
    """Background-read + parallel-decode stream over file shards with the
    retry/quarantine/watchdog/resume discipline described in the module
    docstring.

    ``decode_fn(raw: bytes) -> record`` runs on the worker pool and must be
    pure (a recycled record may be decoded twice).  ``open_fn(path)`` must
    return an iterable of byte lines (injectable for fault tests).
    """

    def __init__(self, shards, decode_fn: Callable[[bytes], Any],
                 config: Optional[StreamConfig] = None, *,
                 host_id: int = 0, num_hosts: int = 1,
                 state: Optional[StreamState] = None,
                 open_fn: Optional[Callable[[str], Any]] = None,
                 name: str = "stream"):
        if shards and not isinstance(shards[0], Shard):
            shards = make_shards(list(shards))
        self.shards: List[Shard] = list(shards)
        if not self.shards:
            raise StreamError("stream has no shards (empty file list)")
        self.decode_fn = decode_fn
        self.config = config or StreamConfig()
        self.host_id = int(host_id)
        self.num_hosts = int(num_hosts)
        self.open_fn = open_fn or (lambda path: open(path, "rb"))
        self.name = name
        shash = shard_list_hash(self.shards)
        if state is not None:
            if state.shard_hash != shash:
                raise StreamError(
                    f"stream state does not match the shard set "
                    f"(state hash {state.shard_hash:#x}, shards {shash:#x})"
                    " — the file list or a file's size changed since the "
                    "checkpoint")
            self.state = state
        else:
            self.state = StreamState(shash, seed=self.config.seed)
        self._skip_counts: Dict[str, int] = {}
        self.quarantine_path = self.config.quarantine_path \
            or _default_quarantine_path()
        self._quarantine_lock = threading.Lock()
        self.quarantined = 0            # this stream's own count
        self.retries = 0
        self.recycles = 0

    # -- resume surface ----------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Batch-boundary-aligned resume token (a deep copy — safe to hand
        to an async checkpoint writer)."""
        return self.state.to_dict()

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        st = StreamState.from_dict(d)
        if st.shard_hash != shard_list_hash(self.shards):
            raise StreamError(
                "restored stream state does not match the current shard "
                "set — the file list or a file's size changed")
        self.state = st

    # -- retry plumbing ----------------------------------------------------

    def _retrying(self, stage: str, shard: Shard, fn):
        pol = self.config.retry
        for attempt in range(1, pol.max_attempts + 1):
            try:
                return fn()
            except (OSError, IOError) as e:
                if attempt >= pol.max_attempts:
                    raise ShardReadError(
                        f"shard {shard.name!r}: {stage} failed after "
                        f"{attempt} attempt(s): {e}") from e
                _m_retries.labels(stage).inc()
                self.retries += 1
                d = pol.delay(attempt)
                logger.warning(
                    "input %s: shard %s %s failed (%s); retry %d/%d in "
                    "%.2fs", self.name, shard.name, stage, e, attempt,
                    pol.max_attempts - 1, d)
                pol.sleep(d)

    def _read_shard(self, shard: Shard, skip: int, stop: threading.Event
                    ) -> Iterator[Tuple[int, bytes]]:
        """Yield ``(raw_index, line)`` from ``skip`` onward, reopening and
        re-seeking (by line count) on mid-read I/O faults.  Blank lines
        advance the index but yield nothing."""
        pol = self.config.retry
        consumed = int(skip)
        read_attempts = 0
        while not stop.is_set():
            f = self._retrying("open", shard,
                               lambda: self.open_fn(shard.path))
            try:
                for i, raw in enumerate(f):
                    if i < consumed:
                        continue
                    if stop.is_set():
                        return
                    line = raw.rstrip(b"\r\n") if isinstance(raw, bytes) \
                        else raw.rstrip("\r\n").encode()
                    if line:
                        yield i, line
                    consumed = i + 1
                return
            except (OSError, IOError) as e:
                read_attempts += 1
                if read_attempts >= pol.max_attempts:
                    raise ShardReadError(
                        f"shard {shard.name!r}: read failed after "
                        f"{read_attempts} attempt(s) at record {consumed}: "
                        f"{e}") from e
                _m_retries.labels("read").inc()
                self.retries += 1
                d = pol.delay(read_attempts)
                logger.warning(
                    "input %s: shard %s read fault at record %d (%s); "
                    "reopening in %.2fs", self.name, shard.name, consumed,
                    e, d)
                pol.sleep(d)
            finally:
                close = getattr(f, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass

    # -- quarantine --------------------------------------------------------

    def _quarantine(self, sname: str, idx: int, raw: bytes,
                    err: BaseException) -> None:
        n = self._skip_counts.get(sname, 0) + 1
        self._skip_counts[sname] = n
        _m_quarantined.inc()
        self.quarantined += 1
        entry = {
            "time": time.time(), "stream": self.name, "shard": sname,
            "record_index": int(idx),
            "error": f"{type(err).__name__}: {err}",
            "raw_prefix": raw[:256].decode("utf-8", "replace"),
        }
        try:
            with self._quarantine_lock, open(self.quarantine_path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError as e:   # the sidecar must never kill training
            logger.warning("input %s: quarantine sidecar write failed: %s",
                           self.name, e)
        logger.warning(
            "input %s: quarantined record %d of shard %s (%s) -> %s "
            "[%d/%d budget]", self.name, idx, sname, entry["error"],
            self.quarantine_path, n, self.config.skip_budget)
        if n > self.config.skip_budget:
            raise QuarantineOverflowError(
                f"shard {sname!r}: {n} corrupt records exceed the skip "
                f"budget ({self.config.skip_budget}) — failing fast; "
                f"inspect the quarantine sidecar at {self.quarantine_path} "
                "and fix or drop the shard")

    # -- stall reporting ---------------------------------------------------

    def _report_stall(self, sname: Optional[str], waited_s: float) -> None:
        logger.warning(
            "input %s: stream stalled for %.1fs waiting on shard %s — the "
            "decode pipeline is not keeping up (slow storage, stuck "
            "tokenizer, or an undersized worker pool)",
            self.name, waited_s, sname or "<unknown>")
        try:
            from ..parallel import health as _health

            d = os.environ.get(_health.ENV_DIR)
        except Exception:
            d = None
        if not d:
            return
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
        path = os.path.join(d, f"input_stall.rank{rank}.json")
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"rank": int(rank), "stream": self.name,
                           "shard": sname, "waited_s": round(waited_s, 3),
                           "time": time.time(), "pid": os.getpid()}, f)
            os.replace(tmp, path)
        except OSError:
            pass

    # -- the pipeline ------------------------------------------------------

    def _events(self) -> Iterator[Tuple]:
        """Yield ``("ok", record, shard_name, raw_idx)`` and
        ``("skip", shard_name, raw_idx)`` events in deterministic record
        order, running read/decode on background threads."""
        cfg = self.config
        order = assign_shards(
            epoch_shard_order(self.shards, self.state.seed,
                              self.state.epoch, cfg.shuffle_shards),
            self.host_id, self.num_hosts)
        stop = threading.Event()
        in_q: _queue.Queue = _queue.Queue(maxsize=2 * cfg.num_workers)
        out_q: _queue.Queue = _queue.Queue(maxsize=cfg.prefetch)
        inflight: Dict[int, Tuple[str, int]] = {}
        meta_lock = threading.Lock()
        feed = {"done": False, "total": 0, "error": None}
        workers: List[_Worker] = []
        workers_lock = threading.Lock()

        def _put(q, item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def feed_loop():
            seq = 0
            try:
                for shard in order:
                    skip = self.state.offsets.get(shard.name, 0)
                    for raw_idx, raw in self._read_shard(shard, skip, stop):
                        with meta_lock:
                            inflight[seq] = (shard.name, raw_idx)
                        if not _put(in_q, (seq, shard.name, raw_idx, raw)):
                            return
                        seq += 1
            except BaseException as e:
                feed["error"] = e
            finally:
                feed["total"] = seq
                feed["done"] = True

        def work_loop(w: _Worker):
            while not stop.is_set() and not w.abandoned:
                try:
                    item = in_q.get(timeout=0.1)
                except _queue.Empty:
                    continue
                seq, sname, idx, raw = item
                w.current = item
                w.busy_since = time.monotonic()
                try:
                    rec = self.decode_fn(raw)
                    if cfg.validate_fn is not None:
                        cfg.validate_fn(rec)
                    res = ("ok", seq, rec, sname, idx)
                except BaseException as e:
                    res = ("bad", seq, sname, idx, raw, e)
                w.current = None
                _put(out_q, res)   # late results from abandoned workers
                if w.abandoned:    # are deduped by seq in the driver
                    return

        def spawn_worker() -> _Worker:
            w = _Worker(len(workers))
            t = threading.Thread(target=work_loop, args=(w,), daemon=True,
                                 name=f"{self.name}-decode-{w.idx}")
            w.thread = t
            t.start()
            return w

        def watchdog_loop():
            tick = max(0.05, min(1.0, cfg.watchdog_deadline_s / 4.0))
            while not stop.is_set():
                time.sleep(tick)
                now = time.monotonic()
                with workers_lock:
                    live = list(workers)
                for w in live:
                    cur = w.current
                    if cur is None or w.abandoned:
                        continue
                    if now - w.busy_since <= cfg.watchdog_deadline_s:
                        continue
                    w.abandoned = True
                    _m_recycles.inc()
                    self.recycles += 1
                    seq, sname, idx, _raw = cur
                    logger.warning(
                        "input %s: decode worker stuck %.1fs on shard %s "
                        "record %d — recycling the worker and "
                        "re-dispatching the record", self.name,
                        now - w.busy_since, sname, idx)
                    with workers_lock:
                        if w in workers:
                            workers.remove(w)
                        workers.append(spawn_worker())
                    _put(in_q, cur)

        feeder = threading.Thread(target=feed_loop, daemon=True,
                                  name=f"{self.name}-read")
        feeder.start()
        with workers_lock:
            for _ in range(cfg.num_workers):
                workers.append(spawn_worker())
        wd = threading.Thread(target=watchdog_loop, daemon=True,
                              name=f"{self.name}-watchdog")
        wd.start()

        pending: Dict[int, Tuple] = {}
        next_emit = 0
        last_progress = time.monotonic()
        warned = False
        # bounded wait: the tick is short enough that a stall at the warn
        # threshold is noticed within ~2 ticks even for small thresholds
        tick = min(0.25, max(0.01, cfg.stall_warn_s / 2.0)) \
            if cfg.stall_warn_s > 0 else 0.25
        try:
            while True:
                if feed["error"] is not None:
                    raise feed["error"]
                if feed["done"] and next_emit >= feed["total"]:
                    return
                t0 = time.perf_counter_ns()
                try:
                    if cfg.charge_goodput:
                        with _gp.timer("input_stall"):
                            res = out_q.get(timeout=tick)
                    else:
                        res = out_q.get(timeout=tick)
                except _queue.Empty:
                    _m_stall_s.inc((time.perf_counter_ns() - t0) / 1e9)
                    waited = time.monotonic() - last_progress
                    if waited > cfg.stall_warn_s and not warned:
                        with meta_lock:
                            slow = inflight.get(next_emit)
                        self._report_stall(slow[0] if slow else None, waited)
                        warned = True
                    continue
                _m_stall_s.inc((time.perf_counter_ns() - t0) / 1e9)
                seq = res[1]
                if seq < next_emit or seq in pending:
                    continue    # duplicate from a recycled worker
                pending[seq] = res
                while next_emit in pending:
                    res = pending.pop(next_emit)
                    with meta_lock:
                        inflight.pop(next_emit, None)
                    next_emit += 1
                    last_progress = time.monotonic()
                    warned = False
                    if res[0] == "ok":
                        _, _seq, rec, sname, idx = res
                        yield ("ok", rec, sname, idx)
                    else:
                        _, _seq, sname, idx, raw, err = res
                        self._quarantine(sname, idx, raw, err)
                        yield ("skip", sname, idx)
        finally:
            stop.set()
            for q in (in_q, out_q):
                try:
                    while True:
                        q.get_nowait()
                except _queue.Empty:
                    pass
            feeder.join(timeout=5)
            wd.join(timeout=5)
            with workers_lock:
                live = list(workers)
            for w in live:
                if not w.abandoned and w.thread is not None:
                    w.thread.join(timeout=5)

    def records(self) -> Iterator[Any]:
        """Decoded records in deterministic order.  NOTE: iterating this
        directly does NOT advance the resume state — use :meth:`batches`
        for checkpointable consumption."""
        for ev in self._events():
            if ev[0] == "ok":
                yield ev[1]

    def batches(self) -> Iterator[List[Any]]:
        """One epoch of record batches.  ``self.state`` (and
        :meth:`state_dict`) is updated ONLY at batch boundaries, so a
        checkpoint taken between yields resumes exactly after the last
        yielded batch.  At epoch end the epoch counter advances and the
        offsets clear; calling again streams the next epoch."""
        cfg = self.config
        batch: List[Any] = []
        pending_offsets: Dict[str, int] = {}
        # the skip budget bounds the corrupt FRACTION of a shard, per
        # epoch pass — a known-tolerable bad record must not accumulate
        # across epochs until it trips the budget on epoch N
        self._skip_counts = {}

        def commit():
            self.state.offsets.update(pending_offsets)
            for sname, off in pending_offsets.items():
                _g_progress.labels(sname).set(off)
            pending_offsets.clear()

        for ev in self._events():
            if ev[0] == "skip":
                pending_offsets[ev[1]] = ev[2] + 1
                continue
            _, rec, sname, idx = ev
            pending_offsets[sname] = idx + 1
            batch.append(rec)
            if len(batch) >= cfg.batch_size:
                commit()
                self.state.records += len(batch)
                _m_records.inc(len(batch))
                _m_batches.inc()
                yield batch
                batch = []
        if batch and not cfg.drop_last:
            commit()
            self.state.records += len(batch)
            _m_records.inc(len(batch))
            _m_batches.inc()
            yield batch
        # epoch complete: advance and clear so the next batches() call (or
        # a resume from the final state) starts the next epoch cleanly
        self.state.epoch += 1
        self.state.offsets = {}


# ---------------------------------------------------------------------------
# Executor-facing dataset adapter (MultiSlot records -> feed dicts)
# ---------------------------------------------------------------------------

class StreamingDataset:
    """A ``train_from_dataset``-compatible dataset over a fault-tolerant
    sharded stream of MultiSlot text records (one instance per line — the
    same wire format as :class:`..dataset.QueueDataset`, with the
    retry/quarantine/resume discipline of :class:`ShardedStream`).

    Iteration yields feed dicts; each carries a ``__stream_state__`` key
    (the batch-aligned resume token) that the Executor pops, keeps, and
    serializes into the elastic checkpoint's ``data_state`` — restoring it
    via :meth:`restore_stream_state` resumes the stream without replaying
    consumed batches (docs/data.md).
    """

    STATE_KEY = "__stream_state__"

    def __init__(self):
        from . import DatasetBase

        # compose (not inherit) the schema/batching surface of DatasetBase
        # so MultiSlot parsing and feed assembly stay one implementation
        self._base = DatasetBase()
        self.stream_options = StreamConfig()
        self._engine: Optional[ShardedStream] = None
        self._restored: Optional[Dict[str, Any]] = None
        self.thread_num = 1     # decode threads live inside the engine

    # -- reference setter surface (delegated) ------------------------------
    def set_batch_size(self, batch_size: int):
        self._base.set_batch_size(batch_size)

    def set_thread(self, thread_num: int):
        self.stream_options.num_workers = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self._base.set_filelist(filelist)
        self._engine = None

    def set_use_var(self, var_list):
        self._base.set_use_var(var_list)

    def set_pad_to(self, maxlen):
        self._base.set_pad_to(maxlen)

    def set_trainer_shard(self, trainer_id: int, trainer_num: int):
        self._base.set_trainer_shard(trainer_id, trainer_num)
        self._engine = None

    def set_stream_options(self, **kw) -> "StreamingDataset":
        """Override StreamConfig fields (retry=, skip_budget=,
        quarantine_path=, watchdog_deadline_s=, num_workers=, ...)."""
        for k, v in kw.items():
            if not hasattr(self.stream_options, k):
                raise ValueError(f"unknown stream option {k!r}")
            setattr(self.stream_options, k, v)
        self._engine = None
        return self

    @property
    def use_vars(self):
        return self._base.use_vars

    @property
    def batch_size(self):
        return self._base.batch_size

    @property
    def drop_last(self):
        return self._base.drop_last

    @drop_last.setter
    def drop_last(self, v):
        self._base.drop_last = bool(v)

    # -- decode ------------------------------------------------------------
    def _decode_line(self, raw: bytes):
        from . import parse_multislot

        is_float, _dims, _dtypes = self._base._slot_schema()
        values, lods = parse_multislot(raw + b"\n", is_float)
        insts = self._base._instances_of(values, lods)
        if len(insts) != 1:
            raise ValueError(
                f"expected exactly 1 MultiSlot instance per line, "
                f"got {len(insts)}")
        return insts[0]

    # -- engine ------------------------------------------------------------
    def _ensure_engine(self) -> ShardedStream:
        if self._engine is None:
            cfg = self.stream_options
            cfg.batch_size = self._base.batch_size
            cfg.drop_last = self._base.drop_last
            state = (StreamState.from_dict(self._restored)
                     if self._restored else None)
            self._engine = ShardedStream(
                self._base.filelist, self._decode_line, cfg,
                host_id=self._base._trainer_id,
                num_hosts=self._base._trainer_num,
                state=state, name="dataset")
            self._restored = None
        return self._engine

    def __iter__(self):
        engine = self._ensure_engine()
        for batch in engine.batches():
            feed = self._base._batch_to_feed(batch)
            feed[self.STATE_KEY] = engine.state_dict()
            yield feed

    # -- executor resume protocol ------------------------------------------
    def stream_state(self) -> Dict[str, Any]:
        """Current resume token (the engine's live state; per-batch aligned
        tokens ride each yielded feed under :data:`STATE_KEY`)."""
        if self._engine is not None:
            return self._engine.state_dict()
        if self._restored is not None:
            return dict(self._restored)
        return StreamState(shard_list_hash(make_shards(self._base.filelist)),
                           seed=self.stream_options.seed).to_dict()

    def restore_stream_state(self, d: Dict[str, Any]) -> None:
        """Install a saved resume token; must be called before iteration
        starts (the Executor does this when the restored checkpoint's
        ``data_state`` carries a ``stream`` entry)."""
        if self._engine is not None:
            self._engine.load_state_dict(d)
        else:
            self._restored = dict(d)
