"""paddle.dataset.voc2012 — parity with python/paddle/dataset/voc2012.py
(train/test/val yield (float32 CHW image, int32 HW segmentation mask) —
voc2012.py:64)."""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train", "test", "val"]

_H = _W = 64            # fixture-sized; reference images are variable-size
_CLASSES = 21
_SIZES = {"train": 64, "test": 16, "val": 16}


def _creator(split):
    def reader():
        rs = fixture_rng("voc2012", split)
        for _ in range(_SIZES[split]):
            img = rs.rand(3, _H, _W).astype(np.float32)
            mask = rs.randint(0, _CLASSES, (_H, _W)).astype(np.int32)
            yield img, mask

    return reader


def train():
    return _creator("train")


def test():
    return _creator("test")


def val():
    return _creator("val")
