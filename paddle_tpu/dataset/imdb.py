"""paddle.dataset.imdb — parity with python/paddle/dataset/imdb.py
(train/test(word_idx) yield ([word ids], 0/1 label); word_dict())."""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train", "test", "word_dict"]

_VOCAB = 5149            # reference imdb vocabulary size ballpark
TRAIN_SIZE = 1024
TEST_SIZE = 256


def word_dict():
    """word -> id map ending with '<unk>' (imdb.py build_dict contract)."""
    d = {f"w{i}": i for i in range(_VOCAB)}
    d["<unk>"] = len(d)
    return d


def _creator(split, n):
    def creator(word_idx):
        unk = word_idx.get("<unk>", len(word_idx) - 1)

        def reader():
            rs = fixture_rng("imdb", split)
            vocab = len(word_idx)
            for _ in range(n):
                label = int(rs.randint(0, 2))
                ln = int(rs.randint(8, 64))
                # real reviews carry high-frequency sentiment words; model
                # that: ~1/3 of tokens come from a small class-specific
                # pool, the rest from the class's half of the vocabulary
                lo, hi = (0, vocab // 2) if label == 0 else (vocab // 2,
                                                             vocab)
                base = rs.randint(lo, hi, ln)
                marker = rs.randint(lo, lo + 16, ln)
                use_marker = rs.rand(ln) < 0.34
                doc = [min(int(m if um else t), unk)
                       for t, m, um in zip(base, marker, use_marker)]
                yield doc, label                    # imdb.py:92

        return reader

    return creator


def train(word_idx):
    return _creator("train", TRAIN_SIZE)(word_idx)


def test(word_idx):
    return _creator("test", TEST_SIZE)(word_idx)
