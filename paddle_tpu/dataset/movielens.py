"""paddle.dataset.movielens — parity with
python/paddle/dataset/movielens.py (records are
usr.value() + mov.value() + [[rating]] — movielens.py:167:
 [uid, gender(0/1), age_bucket, job_id,
  mov_id, [category ids], [title word ids], [rating]]).
"""
from __future__ import annotations

from .common import fixture_rng

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "movie_categories", "user_info",
           "movie_info", "age_table"]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_MOVIES = 400
_N_USERS = 600
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 1000
TRAIN_SIZE = 2048
TEST_SIZE = 512


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, list(self.categories), list(self.title)]


class UserInfo:
    def __init__(self, index, gender, age_idx, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_idx
        self.job_id = job_id

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def _movies():
    rs = fixture_rng("movielens", "movies")
    out = {}
    for i in range(1, _N_MOVIES + 1):
        cats = sorted(set(rs.randint(0, _N_CATEGORIES,
                                     rs.randint(1, 4)).tolist()))
        title = rs.randint(0, _TITLE_VOCAB, rs.randint(1, 6)).tolist()
        out[i] = MovieInfo(i, cats, title)
    return out


def _users():
    rs = fixture_rng("movielens", "users")
    out = {}
    for i in range(1, _N_USERS + 1):
        out[i] = UserInfo(i, "M" if rs.rand() < 0.5 else "F",
                          int(rs.randint(0, len(age_table))),
                          int(rs.randint(0, _N_JOBS)))
    return out


_MOVIES = None
_USERS = None


def _meta():
    global _MOVIES, _USERS
    if _MOVIES is None:
        _MOVIES = _movies()
        _USERS = _users()
    return _MOVIES, _USERS


def _creator(split, n):
    def reader():
        movies, users = _meta()
        rs = fixture_rng("movielens", split)
        for _ in range(n):
            uid = int(rs.randint(1, _N_USERS + 1))
            mid = int(rs.randint(1, _N_MOVIES + 1))
            rating = float(rs.randint(1, 6)) * 2 - 5.0   # movielens.py:162
            yield users[uid].value() + movies[mid].value() + [[rating]]

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)


def get_movie_title_dict():
    return {f"t{i}": i for i in range(_TITLE_VOCAB)}


def max_movie_id():
    return _N_MOVIES


def max_user_id():
    return _N_USERS


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {f"c{i}": i for i in range(_N_CATEGORIES)}


def movie_info():
    return _meta()[0]


def user_info():
    return _meta()[1]
