"""Dataset / DataFeed engine — out-of-Python data path for Rec/PS workloads.

Capability parity with the reference's C++ engine (framework/data_set.cc,
framework/data_feed.cc): a Dataset owns a file list in the MultiSlot text
format (each line = one instance; each slot contributes "<n> v1 ... vn"
tokens — uint64 ids for sparse slots, floats for dense slots), supports
in-memory load + local/global shuffle + file-list sharding across trainers,
and feeds the Executor's ``train_from_dataset`` loop.

The parsing hot path is native C++ (paddle_tpu/native/slot_parser.cpp, the
analogue of MultiSlotDataFeed::ParseOneInstance at data_feed.cc:~700), loaded
via ctypes with a pure-Python fallback.

TPU-first batching decision: the reference emits LoDTensors with ragged
offsets; XLA wants static shapes, so variable-length id slots are emitted as
padded ``[batch, maxlen]`` int64 arrays (pad id 0) plus a ``<slot>__len``
int64 length vector when requested — the same information content as LoD,
in a compiler-friendly layout.
"""
from __future__ import annotations

import ctypes
import glob as _glob
import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework.core import convert_dtype
from ..framework.program import Variable

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset", "QueueDataset",
           "StreamingDataset"]


# ---------------------------------------------------------------------------
# native parser binding
# ---------------------------------------------------------------------------

_lib = None
_lib_tried = False


def _native_lib():
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        try:
            from .. import native
            lib = native.load_library("slot_parser")
            lib.ps_parse.restype = ctypes.c_void_p
            lib.ps_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_ubyte),
                                     ctypes.c_int64]
            lib.ps_num_instances.restype = ctypes.c_int64
            lib.ps_num_instances.argtypes = [ctypes.c_void_p]
            lib.ps_error_line.restype = ctypes.c_int
            lib.ps_error_line.argtypes = [ctypes.c_void_p]
            lib.ps_slot_fvals.restype = ctypes.POINTER(ctypes.c_double)
            lib.ps_slot_fvals.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.POINTER(ctypes.c_int64)]
            lib.ps_slot_ivals.restype = ctypes.POINTER(ctypes.c_uint64)
            lib.ps_slot_ivals.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.POINTER(ctypes.c_int64)]
            lib.ps_slot_lod.restype = ctypes.POINTER(ctypes.c_int64)
            lib.ps_slot_lod.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_int64)]
            lib.ps_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib = None
    return _lib


def parse_multislot(text: bytes, slot_is_float: Sequence[bool],
                    force_python: bool = False
                    ) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Parse a MultiSlot text buffer.

    Returns (values, lods): per slot, a flat value array (float64 or uint64)
    and an int64 offsets array of length n_instances+1.
    """
    lib = None if force_python else _native_lib()
    flags = list(bool(f) for f in slot_is_float)
    if lib is not None:
        n_slots = len(flags)
        flag_arr = (ctypes.c_ubyte * n_slots)(*[1 if f else 0 for f in flags])
        h = lib.ps_parse(text, len(text), flag_arr, n_slots)
        try:
            if lib.ps_error_line(h) >= 0:
                raise ValueError(
                    f"malformed MultiSlot record at line {lib.ps_error_line(h)}")
            values, lods = [], []
            n = ctypes.c_int64()
            for s in range(n_slots):
                if flags[s]:
                    ptr = lib.ps_slot_fvals(h, s, ctypes.byref(n))
                    vals = (np.ctypeslib.as_array(ptr, shape=(n.value,)).copy()
                            if n.value else np.empty((0,), np.float64))
                else:
                    ptr = lib.ps_slot_ivals(h, s, ctypes.byref(n))
                    vals = (np.ctypeslib.as_array(ptr, shape=(n.value,)).copy()
                            if n.value else np.empty((0,), np.uint64))
                lptr = lib.ps_slot_lod(h, s, ctypes.byref(n))
                lod = np.ctypeslib.as_array(lptr, shape=(n.value,)).copy()
                values.append(vals)
                lods.append(lod)
            return values, lods
        finally:
            lib.ps_free(h)
    # Python fallback
    values_py: List[List[float]] = [[] for _ in flags]
    lods_py: List[List[int]] = [[0] for _ in flags]
    for line_no, line in enumerate(text.decode("utf-8").splitlines()):
        toks = line.split()
        if not toks:
            continue
        pos = 0
        parsed: List[List[float]] = []
        try:
            for is_f in flags:
                cnt = int(toks[pos]); pos += 1
                if pos + cnt > len(toks):
                    raise IndexError
                conv = float if is_f else int
                parsed.append([conv(t) for t in toks[pos:pos + cnt]])
                pos += cnt
        except (ValueError, IndexError):
            raise ValueError(f"malformed MultiSlot record at line {line_no}")
        for s, vals in enumerate(parsed):
            values_py[s].extend(vals)
            lods_py[s].append(len(values_py[s]))
    return ([np.asarray(v, dtype=np.float64 if f else np.uint64)
             for v, f in zip(values_py, flags)],
            [np.asarray(l, dtype=np.int64) for l in lods_py])


# ---------------------------------------------------------------------------
# Dataset
# ---------------------------------------------------------------------------

class DatasetBase:
    """Common config surface — python/paddle/fluid/dataset.py DatasetBase."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_vars: List[Variable] = []
        self.drop_last = False
        self.emit_lengths = False  # also yield <slot>__len vectors
        self.pad_to: Optional[int] = None  # fixed sparse-slot pad length
        self._trainer_id = 0
        self._trainer_num = 1

    # reference setter surface
    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist: Sequence[str]):
        out: List[str] = []
        for f in filelist:
            hits = sorted(_glob.glob(f))
            out.extend(hits if hits else [f])
        self.filelist = out

    def set_use_var(self, var_list: Sequence[Variable]):
        self.use_vars = list(var_list)

    def set_hdfs_config(self, fs_name, fs_ugi):  # accepted for parity
        pass

    def set_trainer_shard(self, trainer_id: int, trainer_num: int):
        """File-list sharding across trainers (data_set.cc file dispatch)."""
        self._trainer_id = trainer_id
        self._trainer_num = trainer_num

    def set_pad_to(self, maxlen: Optional[int]):
        """Fix the padded length of sparse id slots.  None (default) buckets
        the per-batch max up to the next power of two, so the Executor's jit
        cache sees O(log maxlen) distinct shapes instead of one per batch."""
        self.pad_to = maxlen

    # -- schema -------------------------------------------------------------
    def _slot_schema(self):
        if not self.use_vars:
            raise ValueError("call set_use_var before reading the dataset")
        is_float, dims, dtypes = [], [], []
        for v in self.use_vars:
            np_dt = np.dtype(convert_dtype(v.dtype))
            is_float.append(np_dt.kind == "f")
            static = [d for d in v.shape if d not in (-1, None)]
            dims.append(int(np.prod(static)) if static else 1)
            dtypes.append(np_dt)
        return is_float, dims, dtypes

    def _my_files(self):
        return [f for i, f in enumerate(self.filelist)
                if i % self._trainer_num == self._trainer_id]

    def _parse_file(self, path: str):
        is_float, _, _ = self._slot_schema()
        with open(path, "rb") as f:
            return parse_multislot(f.read(), is_float)

    def _instances_of(self, values, lods):
        """Decompose parsed columnar data back into per-instance tuples of
        per-slot value arrays (needed for shuffling)."""
        n = len(lods[0]) - 1
        out = []
        for i in range(n):
            inst = tuple(vals[lod[i]:lod[i + 1]]
                         for vals, lod in zip(values, lods))
            out.append(inst)
        return out

    def _batch_to_feed(self, instances) -> Dict[str, np.ndarray]:
        is_float, dims, dtypes = self._slot_schema()
        feed: Dict[str, np.ndarray] = {}
        for s, var in enumerate(self.use_vars):
            col = [inst[s] for inst in instances]
            if is_float[s]:
                # dense slot: every instance must carry dims[s] values
                arr = np.stack([c.astype(dtypes[s]) for c in col])
                static = [d for d in var.shape if d not in (-1, None)]
                if static:
                    arr = arr.reshape((len(col), *static))
                feed[var.name] = arr
            else:
                maxlen = max((len(c) for c in col), default=1) or 1
                if self.pad_to is not None:
                    if maxlen > self.pad_to:
                        raise ValueError(
                            f"slot '{var.name}' has an instance with {maxlen} "
                            f"ids > set_pad_to({self.pad_to})")
                    maxlen = self.pad_to
                else:
                    # bucket to next power of two: static-shape friendliness
                    # without a user-declared bound (see module docstring)
                    maxlen = 1 << (maxlen - 1).bit_length()
                padded = np.zeros((len(col), maxlen), dtype=np.int64)
                lens = np.zeros((len(col),), dtype=np.int64)
                for i, c in enumerate(col):
                    padded[i, :len(c)] = c.astype(np.int64)
                    lens[i] = len(c)
                feed[var.name] = padded
                if self.emit_lengths:
                    feed[var.name + "__len"] = lens
        return feed


def _chunk_stream(instances, batch_size, drop_last):
    """Group an instance iterator into batch-sized chunks — the ONE batching
    rule shared by sequential iteration and the threaded pipeline."""
    pending = []
    for inst in instances:
        pending.append(inst)
        if len(pending) == batch_size:
            yield pending
            pending = []
    if pending and not drop_last:
        yield pending


class InMemoryDataset(DatasetBase):
    """load_into_memory + local/global shuffle — data_set.cc InMemoryDataset."""

    def __init__(self):
        super().__init__()
        self._memory: List[Tuple[np.ndarray, ...]] = []

    def load_into_memory(self):
        self._memory = []
        for path in self._my_files():
            values, lods = self._parse_file(path)
            self._memory.extend(self._instances_of(values, lods))

    def local_shuffle(self):
        random.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num: int = 12):
        # single-host capability: reference RPC-shuffles across trainers
        # (data_set.cc GlobalShuffle); with one host this is a local shuffle.
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def __iter__(self):
        for chunk in _chunk_stream(iter(self._memory), self.batch_size,
                                   self.drop_last):
            yield self._batch_to_feed(chunk)


class QueueDataset(DatasetBase):
    """Streaming file-at-a-time dataset — data_set.cc QueueDataset (no
    in-memory materialization; instances flow straight to batches)."""

    def _instance_stream(self):
        for path in self._my_files():
            values, lods = self._parse_file(path)
            yield from self._instances_of(values, lods)

    def __iter__(self):
        for chunk in _chunk_stream(self._instance_stream(), self.batch_size,
                                   self.drop_last):
            yield self._batch_to_feed(chunk)


class DatasetFactory:
    """fluid.DatasetFactory().create_dataset(name) — dataset.py factory."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        if datafeed_class == "StreamingDataset":
            # fault-tolerant sharded streaming (docs/data.md): retry/
            # backoff on shard I/O, corrupt-record quarantine, worker
            # watchdog, deterministic checkpointed resume
            from .streaming import StreamingDataset

            return StreamingDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")


# ---------------------------------------------------------------------------
# threaded batch pipeline (multi_trainer.cc / hogwild_worker.cc capability)
# ---------------------------------------------------------------------------

def iter_batches_threaded(dataset: DatasetBase, threads: int,
                          prefetch: int = 4):
    """Produce batch feed dicts with file parsing and batch assembly
    overlapped with consumption.

    The reference runs N HogwildWorker threads each driving its own DataFeed
    (framework/hogwild_worker.cc, multi_trainer.cc); on TPU the device is
    driven by one dispatch stream, so the equivalent is a producer pool:
    files parse concurrently (a bounded window of in-flight parses),
    `_batch_to_feed` assembly runs in the pool, and a bounded queue keeps
    at most `prefetch` ready batches ahead of the (asynchronously
    dispatching) Executor loop — backpressure everywhere, so a streaming
    QueueDataset never materializes in memory. Batch order is identical to
    the sequential iterator.

    ``Executor.train_from_dataset`` stacks ``reader.prefetch_to_device`` on
    top of this iterator, so host->device transfer of the next batch also
    overlaps the in-flight (asynchronously fetched) step; the assembled
    numpy batches yielded here are consumed without an extra host copy.
    """
    import queue as queue_mod
    import threading as threading_mod
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    threads = max(1, int(threads))
    out_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=max(2, prefetch))
    stop = threading_mod.Event()
    _END = object()

    def put(item) -> bool:
        """Bounded put that aborts when the consumer abandoned us."""
        while not stop.is_set():
            try:
                out_q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def produce(pool):
        bs = dataset.batch_size
        try:
            if isinstance(dataset, InMemoryDataset):
                chunks = _chunk_stream(iter(dataset._memory), bs,
                                       dataset.drop_last)
                for chunk in chunks:
                    # put blocks when the queue is full, bounding the
                    # number of outstanding _batch_to_feed futures
                    if not put(pool.submit(dataset._batch_to_feed, chunk)):
                        return
            else:
                files = dataset._my_files()
                window: deque = deque()
                idx = 0

                def windowed_instances():
                    # instance stream with a bounded window of in-flight
                    # parses; the SAME _chunk_stream as sequential iteration
                    # groups it, so batching cannot drift between paths
                    nonlocal idx
                    while idx < len(files) or window:
                        while idx < len(files) and len(window) < 2 * threads:
                            window.append(
                                pool.submit(dataset._parse_file, files[idx]))
                            idx += 1
                        values, lods = window.popleft().result()
                        yield from dataset._instances_of(values, lods)

                for chunk in _chunk_stream(windowed_instances(), bs,
                                           dataset.drop_last):
                    if not put(pool.submit(dataset._batch_to_feed, chunk)):
                        return
        except BaseException as e:  # surface in the consumer (a swallowed
            put(e)                  # producer death would hang the loop)
        finally:
            put(_END)

    pool = ThreadPoolExecutor(max_workers=threads,
                              thread_name_prefix="dataset_worker")
    producer = threading_mod.Thread(target=produce, args=(pool,), daemon=True)
    producer.start()
    try:
        while True:
            item = out_q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item.result()
    finally:
        stop.set()
        # drain so a blocked producer can observe the stop flag promptly
        try:
            while True:
                out_q.get_nowait()
        except Exception:
            pass
        producer.join(timeout=5)
        pool.shutdown(wait=False, cancel_futures=True)


# ---------------------------------------------------------------------------
# paddle.dataset built-in dataset loaders (reference python/paddle/dataset):
# deterministic local fixtures, no network — see each submodule.
# ---------------------------------------------------------------------------
from . import (  # noqa: F401,E402
    cifar, common, conll05, flowers, image, imdb, imikolov, mnist,
    movielens, mq2007, sentiment, uci_housing, voc2012, wmt14, wmt16,
)

# fault-tolerant sharded streaming engine (ISSUE 11, docs/data.md) —
# imported last: it composes DatasetBase/parse_multislot from this module
from .streaming import StreamingDataset  # noqa: E402
