"""paddle.dataset.conll05 — parity with python/paddle/dataset/conll05.py
(get_dict:209 returns (word, verb, label) dicts; test:~220 yields the
9-slot SRL record — conll05.py:199:
 word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_idx, mark, label).
"""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["get_dict", "get_embedding", "test", "UNK_IDX"]

UNK_IDX = 0
_WORDS = 1000
_VERBS = 50
_LABELS = 59            # reference SRL label-dict size ballpark
TEST_SIZE = 256
_EMB_DIM = 32


def get_dict():
    word_dict = {f"w{i}": i for i in range(_WORDS)}
    verb_dict = {f"v{i}": i for i in range(_VERBS)}
    label_dict = {f"l{i}": i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rs = fixture_rng("conll05", "emb")
    return rs.randn(_WORDS, _EMB_DIM).astype(np.float32)


def test():
    def reader():
        rs = fixture_rng("conll05", "test")
        for _ in range(TEST_SIZE):
            ln = int(rs.randint(4, 30))
            words = rs.randint(0, _WORDS, ln).tolist()
            verb = int(rs.randint(0, _VERBS))
            vpos = int(rs.randint(0, ln))
            mark = [1 if i == vpos else 0 for i in range(ln)]
            labels = rs.randint(0, _LABELS, ln).tolist()
            ctx = [[int(words[max(0, min(ln - 1, vpos + d))])] * ln
                   for d in (-2, -1, 0, 1, 2)]
            yield (words, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4],
                   [verb] * ln, mark, labels)

    return reader
