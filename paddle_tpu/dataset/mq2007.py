"""paddle.dataset.mq2007 — parity with python/paddle/dataset/mq2007.py
(LETOR learning-to-rank: 46-dim feature vectors grouped per query;
train/test readers in pointwise/pairwise/listwise formats).
Deterministic fixture per common.py."""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train", "test"]

_FEATURES = 46
_QUERIES = {"train": 64, "test": 16}
_DOCS_PER_QUERY = (8, 20)


def _queries(split):
    rs = fixture_rng("mq2007", split)
    out = []
    for qid in range(_QUERIES[split]):
        n = int(rs.randint(*_DOCS_PER_QUERY))
        feats = rs.rand(n, _FEATURES).astype(np.float32)
        rel = rs.randint(0, 3, n)            # LETOR relevance in {0,1,2}
        out.append((qid, rel, feats))
    return out


def _creator(split, format):
    if format not in ("pointwise", "pairwise", "listwise"):
        raise ValueError(
            f"mq2007 format must be pointwise/pairwise/listwise, "
            f"got {format!r}")

    def reader():
        for qid, rel, feats in _queries(split):
            if format == "pointwise":
                for r, f in zip(rel, feats):
                    yield float(r), f
            elif format == "pairwise":
                for i in range(len(rel)):
                    for j in range(len(rel)):
                        if rel[i] > rel[j]:
                            yield 1.0, feats[i], feats[j]
            else:                            # listwise
                yield qid, [float(r) for r in rel], feats

    return reader


def train(format="pairwise"):
    return _creator("train", format)


def test(format="pairwise"):
    return _creator("test", format)
