"""paddle.dataset.image — parity with python/paddle/dataset/image.py
(resize_short:197, to_chw:225, center_crop:249, random_crop:277,
left_right_flip:305, simple_transform:327).

Pure-numpy implementations (the reference shells out to cv2; the image
math here is the same — bilinear resize, crops, flips, CHW transpose)."""
from __future__ import annotations

import numpy as np

__all__ = ["resize_short", "to_chw", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "load_and_transform"]


def _bilinear_resize(im, h, w):
    ih, iw = im.shape[:2]
    ys = np.clip((np.arange(h) + 0.5) * ih / h - 0.5, 0, ih - 1)
    xs = np.clip((np.arange(w) + 0.5) * iw / w - 0.5, 0, iw - 1)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    r0 = im[y0]
    r1 = im[y1]
    top = r0[:, x0] * (1 - wx) + r0[:, x1] * wx
    bot = r1[:, x0] * (1 - wx) + r1[:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(im.dtype, np.integer):
        out = np.rint(out)      # cv2 INTER_LINEAR rounds; truncation would
    return out.astype(im.dtype)  # bias integer images dark by up to 1 LSB


def resize_short(im, size):
    """image.py:197 — scale so the SHORT side equals size."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    return _bilinear_resize(im, nh, nw)


def to_chw(im, order=(2, 0, 1)):
    """image.py:225 — HWC -> CHW."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """image.py:249."""
    h, w = im.shape[:2]
    hs = max((h - size) // 2, 0)
    ws = max((w - size) // 2, 0)
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True, rng=None):
    """image.py:277."""
    rng = rng or np.random
    h, w = im.shape[:2]
    hs = rng.randint(0, max(h - size, 0) + 1)
    ws = rng.randint(0, max(w - size, 0) + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    """image.py:305."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """image.py:327 — resize_short, crop (random+flip when training,
    center otherwise), CHW, float32, optional mean subtraction."""
    im = resize_short(im, resize_size)
    rng = rng or np.random
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if rng.randint(0, 2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]      # per-channel over CHW
        im = im - mean                      # scalar/full-shape broadcast
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """image.py:383 — .npy fixtures replace cv2.imread (no cv2 in env)."""
    im = np.load(filename) if str(filename).endswith(".npy") else None
    if im is None:
        raise ValueError(
            "load_and_transform supports .npy image fixtures in this "
            "environment (no cv2); got " + str(filename))
    return simple_transform(im, resize_size, crop_size, is_train,
                            is_color=is_color, mean=mean)
