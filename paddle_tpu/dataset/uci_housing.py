"""paddle.dataset.uci_housing — parity with
python/paddle/dataset/uci_housing.py (train:85/test:~105 yield
(float32[13] normalized features, float32[1] price)).

Deterministic fixture: features ~ N(0,1) after the reference's
feature_range normalization; price = a fixed linear model + noise so
fit_a_line genuinely converges.
"""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

_W = np.linspace(-1.5, 1.5, 13).astype(np.float32)


def _make(split, n):
    rs = fixture_rng("uci_housing", split)
    x = rs.randn(n, 13).astype(np.float32)
    y = (x @ _W + 22.5 + rs.randn(n).astype(np.float32) * 0.3)
    return x, y.astype(np.float32)


def _creator(split, n):
    def reader():
        x, y = _make(split, n)
        for i in range(n):
            yield x[i], y[i:i + 1]

    return reader


def train():
    return _creator("train", 404)


def test():
    return _creator("test", 102)
