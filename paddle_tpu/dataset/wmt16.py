"""paddle.dataset.wmt16 — parity with python/paddle/dataset/wmt16.py
(train/test/validation(src_dict_size, trg_dict_size) yield
(src_ids, trg_ids, trg_ids_next) — wmt16.py:142; get_dict)."""
from __future__ import annotations

from .common import fixture_rng

__all__ = ["train", "test", "validation", "get_dict"]

_START, _END, _UNK = 0, 1, 2
_SIZES = {"train": 512, "test": 128, "validation": 128}


def get_dict(lang, dict_size, reverse=False):
    d = {"<s>": 0, "<e>": 1, "<unk>": 2}
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _creator(split, src_dict_size, trg_dict_size):
    def reader():
        rs = fixture_rng("wmt16", split)
        for _ in range(_SIZES[split]):
            sl = int(rs.randint(3, 28))
            tl = int(rs.randint(3, 28))
            src = rs.randint(3, src_dict_size, sl).tolist()
            trg = rs.randint(3, trg_dict_size, tl).tolist()
            yield src, [_START] + trg, trg + [_END]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("train", src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("test", src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("validation", src_dict_size, trg_dict_size)
