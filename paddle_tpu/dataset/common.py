"""paddle.dataset.common — parity with python/paddle/dataset/common.py.

The reference's common module downloads archives into ~/.cache/paddle/
dataset and md5-checks them.  This environment has no network, so every
dataset here is a DETERMINISTIC LOCAL FIXTURE: records are synthesized
once per (dataset, split) from a fixed seed and cached in-process.  The
record SCHEMAS match the reference loaders exactly (shapes, dtypes, value
ranges, normalization), so reader-consuming programs (paddle.batch +
DataFeeder + the book examples) run unchanged; only the pixel/token
content is synthetic.
"""
from __future__ import annotations

import os
import tempfile

import numpy as np

__all__ = ["DATA_HOME", "md5file", "split", "cluster_files_reader",
           "fixture_rng"]

DATA_HOME = os.path.join(
    os.environ.get("PADDLE_TPU_DATA_HOME",
                   os.path.join(tempfile.gettempdir(), "paddle_tpu")),
    "dataset")


def fixture_rng(name: str, split: str) -> np.random.RandomState:
    """The deterministic generator every fixture dataset derives from.
    crc32, not hash(): python salts str hashes per process, which would
    make every run train on different fixture data."""
    import zlib

    seed = (zlib.crc32(f"{name}:{split}".encode()) & 0x7FFFFFFF) or 1
    return np.random.RandomState(seed)


def md5file(fname):
    import hashlib

    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """reference common.split — dump a reader into chunked pickle files."""
    import pickle

    indx_f = 0
    batch = []
    out_files = []

    def _dump(records, idx):
        fname = suffix % idx
        with open(fname, "wb") as f:
            (dumper or pickle.dump)(records, f)
        out_files.append(fname)

    for item in reader():
        batch.append(item)
        if len(batch) == line_count:
            _dump(batch, indx_f)
            indx_f += 1
            batch = []
    if batch:
        _dump(batch, indx_f)
    return out_files


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """reference common.cluster_files_reader — shard pickled chunks.

    An empty shard assignment is a hard error, not a silent empty reader:
    a trainer that matches no files (bad pattern) or draws none from the
    round-robin split (``trainer_id`` beyond the file count) would
    otherwise train on nothing while its loss never moves (ISSUE 11
    satellite; :func:`paddle_tpu.dataset.streaming.assign_shards` applies
    the same rule to streaming shards)."""
    import glob
    import pickle

    def reader():
        flist = sorted(glob.glob(files_pattern))
        if not flist:
            raise ValueError(
                f"cluster_files_reader: pattern {files_pattern!r} matched "
                "no files")
        my = flist[trainer_id::trainer_count]
        if not my:
            raise ValueError(
                f"cluster_files_reader: trainer {trainer_id}/"
                f"{trainer_count} is assigned no files ({len(flist)} "
                "file(s) total) — fewer matching files than trainers; "
                "reduce trainer_count or split the input")

        def gen():
            for fn in my:
                with open(fn, "rb") as f:
                    for item in (loader or pickle.load)(f):
                        yield item

        return gen()

    return reader
