"""paddle.dataset.flowers — parity with python/paddle/dataset/flowers.py
(train/test/valid yield (float32[3*224*224] image, int label in [0,102))
— flowers.py:136)."""
from __future__ import annotations

import numpy as np

from .common import fixture_rng

__all__ = ["train", "test", "valid"]

_CLASSES = 102
_DIM = 3 * 224 * 224
_SIZES = {"train": 256, "test": 64, "valid": 64}


def _creator(split, use_xmap=True):
    def reader():
        rs = fixture_rng("flowers", split)
        for _ in range(_SIZES[split]):
            label = int(rs.randint(0, _CLASSES))
            img = np.clip(
                np.full(_DIM, (label + 0.5) / _CLASSES, np.float32)
                + rs.rand(_DIM).astype(np.float32) * 0.2, 0, 1)
            yield img, label

    return reader


def train(use_xmap=True):
    return _creator("train", use_xmap)


def test(use_xmap=True):
    return _creator("test", use_xmap)


def valid(use_xmap=True):
    return _creator("valid", use_xmap)
