"""paddle.dataset.wmt14 — parity with python/paddle/dataset/wmt14.py
(train/test(dict_size) yield (src_ids, trg_ids, trg_ids_next) —
wmt14.py:112)."""
from __future__ import annotations

from .common import fixture_rng

__all__ = ["train", "test", "N"]

N = 30              # reference slices long sentences at N tokens
_START, _END, _UNK = 0, 1, 2
TRAIN_SIZE = 512
TEST_SIZE = 128


def _creator(split, n, dict_size):
    def reader():
        rs = fixture_rng("wmt14", split)
        for _ in range(n):
            sl = int(rs.randint(3, N - 2))
            tl = int(rs.randint(3, N - 2))
            src = rs.randint(3, dict_size, sl).tolist()
            trg = rs.randint(3, dict_size, tl).tolist()
            yield src, [_START] + trg, trg + [_END]     # wmt14.py:108-112

    return reader


def train(dict_size):
    return _creator("train", TRAIN_SIZE, dict_size)


def test(dict_size):
    return _creator("test", TEST_SIZE, dict_size)
