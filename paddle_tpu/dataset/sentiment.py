"""paddle.dataset.sentiment — parity with
python/paddle/dataset/sentiment.py (train/test yield ([word ids], 0/1) —
sentiment.py:130; get_word_dict)."""
from __future__ import annotations

from .common import fixture_rng

__all__ = ["train", "test", "get_word_dict"]

_VOCAB = 800
TRAIN_SIZE = 512
TEST_SIZE = 128


def get_word_dict():
    return [(f"w{i}", i) for i in range(_VOCAB)]


def _creator(split, n):
    def reader():
        rs = fixture_rng("sentiment", split)
        for _ in range(n):
            label = int(rs.randint(0, 2))
            ln = int(rs.randint(5, 40))
            lo, hi = (0, _VOCAB // 2) if label else (_VOCAB // 2, _VOCAB)
            yield rs.randint(lo, hi, ln).tolist(), label

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)
