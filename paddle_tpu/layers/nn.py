"""Layer functions building IR ops — parity with python/paddle/fluid/layers/nn.py
(15,019 LoC, 155 public layer fns). Each appends OpDescs via LayerHelper; no
computation happens here — the Executor compiles the whole program to XLA.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..framework.core import VarType
from ..framework.layer_helper import LayerHelper
from ..framework.initializer import ConstantInitializer
from ..framework.program import Variable, default_main_program

__all__ = [
    "data", "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose", "pool2d",
    "adaptive_pool2d", "batch_norm", "layer_norm", "instance_norm", "group_norm",
    "dropout", "relu", "relu6", "leaky_relu", "elu", "gelu", "sigmoid", "tanh",
    "softmax", "log_softmax", "softplus", "swish", "hard_sigmoid", "hard_swish",
    "prelu", "cross_entropy", "softmax_with_cross_entropy", "mse_loss",
    "sigmoid_cross_entropy_with_logits", "smooth_l1", "huber_loss", "kldiv_loss",
    "square_error_cost", "matmul", "mul", "topk", "accuracy", "one_hot",
    "label_smooth", "pad", "pad2d", "resize_nearest", "resize_bilinear",
    "l2_normalize", "clip", "clip_by_norm", "mean", "pow", "unfold",
    "continuous_value_model", "data_norm", "nce", "py_func",
    "sampled_softmax_with_cross_entropy", "shuffle_batch",
]


def data(
    name: str,
    shape: Sequence[int],
    dtype: str = "float32",
    append_batch_size: bool = True,
    lod_level: int = 0,
    stop_gradient: bool = True,
):
    """fluid.layers.data / fluid.data — declare a feed slot.

    Note: like fluid.layers.data, a leading batch dim of -1 is prepended when
    append_batch_size is True.
    """
    helper = LayerHelper("data")
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.global_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=stop_gradient,
        need_check_feed=True,
    )


def fc(
    input,
    size: int,
    num_flatten_dims: int = 1,
    param_attr=None,
    bias_attr=None,
    act: Optional[str] = None,
    name: Optional[str] = None,
):
    """Fully-connected — reference fluid/layers/nn.py fc (mul + sum + bias + act)."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        input_shape = inp.shape
        in_features = int(np.prod(input_shape[num_flatten_dims:]))
        w = helper.create_parameter(
            param_attr, shape=[in_features, size], dtype=inp.dtype
        )
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op(type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse: bool = False,
    is_distributed: bool = False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """reference fluid/layers/nn.py embedding → lookup_table op.

    is_sparse/is_distributed are accepted for API parity; on TPU the gradient
    is a dense scatter-add (segment-sum) which XLA handles natively, and the
    distributed path shards the table over the mesh (see parallel/)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype)
    w.is_distributed = is_distributed
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table",
        inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [out]},
        attrs={"padding_idx": padding_idx, "is_sparse": is_sparse,
               "is_distributed": is_distributed},
    )
    return out


def conv2d(
    input,
    num_filters: int,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups: int = 1,
    param_attr=None,
    bias_attr=None,
    use_cudnn: bool = True,  # accepted, ignored (XLA owns conv lowering)
    act: Optional[str] = None,
    name: Optional[str] = None,
    data_format: str = "NCHW",
):
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    channel_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[channel_axis]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    filter_shape = [num_filters, num_channels // groups] + list(fsize)

    import math
    from ..framework.initializer import NormalInitializer

    fan_in = (num_channels // groups) * int(np.prod(fsize))
    default_init = NormalInitializer(0.0, math.sqrt(2.0 / fan_in))
    w = helper.create_parameter(
        param_attr, shape=filter_shape, dtype=input.dtype,
        default_initializer=default_init,
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
            "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
            "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 2,
            "groups": groups,
            "data_format": data_format,
        },
    )
    if bias_attr is not False:
        pre_act = helper.append_bias_op(out, dim_start=channel_axis, dim_end=channel_axis + 1)
    else:
        pre_act = out
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True, act=None,
           name=None):
    helper = LayerHelper("conv3d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 3
    w = helper.create_parameter(
        param_attr, shape=[num_filters, num_channels // groups] + list(fsize),
        dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 3,
            "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 3,
            "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 3,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2) if bias_attr is not False else out
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    w = helper.create_parameter(
        param_attr, shape=[num_channels, num_filters // groups] + list(fsize),
        dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
            "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
            "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 2,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2) if bias_attr is not False else out
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True, data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(pool_size) if isinstance(pool_size, (list, tuple)) else [pool_size] * 2,
            "strides": list(pool_stride) if isinstance(pool_stride, (list, tuple)) else [pool_stride] * 2,
            "paddings": list(pool_padding) if isinstance(pool_padding, (list, tuple)) else [pool_padding] * 2,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False, name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(pool_size) if isinstance(pool_size, (list, tuple)) else [pool_size] * 2,
            "adaptive": True,
        },
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW", in_place=False,
               name=None, moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False):
    helper = LayerHelper("batch_norm", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = "float32"  # stats/scale in f32 even for bf16 activations
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype, is_bias=True)

    from ..framework.param_attr import ParamAttr

    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=[c], dtype=dtype, default_initializer=ConstantInitializer(0.0))
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=[c], dtype=dtype, default_initializer=ConstantInitializer(1.0))

    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout, "use_global_stats": use_global_stats},
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype="float32",
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape, dtype="float32", is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    var = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("instance_norm", name=name)
    c = input.shape[1]
    scale = helper.create_parameter(param_attr, shape=[c], dtype=input.dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    sm = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    sv = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="instance_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
        outputs={"Y": [out], "SavedMean": [sm], "SavedVariance": [sv]},
        attrs={"epsilon": epsilon},
    )
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", act=act, name=name)
    c = input.shape[1]
    scale = helper.create_parameter(param_attr, shape=[c], dtype=input.dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias]},
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "dropout_implementation": dropout_implementation,
               "seed": seed if seed is not None else 0},
    )
    return out


def _unary_layer(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


relu = _unary_layer("relu")
relu6 = _unary_layer("relu6")
sigmoid = _unary_layer("sigmoid")
tanh = _unary_layer("tanh")
softplus = _unary_layer("softplus")
swish = _unary_layer("swish")
hard_sigmoid = _unary_layer("hard_sigmoid")
hard_swish = _unary_layer("hard_swish")


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="leaky_relu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"alpha": alpha})
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper("elu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="elu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"alpha": alpha})
    return out


def gelu(x, approximate=False, name=None):
    helper = LayerHelper("gelu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="gelu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"approximate": approximate})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    alpha_shape = [1] if mode == "all" else [x.shape[1]] if mode == "channel" else list(x.shape[1:])
    alpha = helper.create_parameter(param_attr, shape=alpha_shape, dtype=x.dtype,
                                    default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="softmax", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="log_softmax", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1,
                               vocab_chunk=0):
    """``vocab_chunk > 0`` selects the chunked lowering (docs/memory_levers.md):
    loss and its backward are blocked over the class axis so the f32
    softmax intermediates never materialize at full vocab width. The
    Softmax output is not produced in that mode."""
    if vocab_chunk and (return_softmax or soft_label):
        raise ValueError(
            "vocab_chunk CE does not materialize the softmax; "
            "return_softmax/soft_label need vocab_chunk=0")
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis,
               "vocab_chunk": int(vocab_chunk)},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="mse_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    from .tensor import reduce_mean

    return reduce_mean(out)


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="mse_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    diff = helper.create_variable_for_type_inference(x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="smooth_l1_loss",
        inputs={"X": [x], "Y": [y]},
        outputs={"Diff": [diff], "Out": [out]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Residual": [residual], "Out": [out]},
        attrs={"delta": delta},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="kldiv_loss",
        inputs={"X": [x], "Target": [target]},
        outputs={"Loss": [out]},
        attrs={"reduction": reduction},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": alpha},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k if isinstance(k, int) else 1},
    )
    return values, indices


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference("int32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="one_hot",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth, "allow_out_of_range": allow_out_of_range},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    from .tensor import scale as scale_layer

    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    num_classes = label.shape[-1]
    helper.append_op(
        type="scale",
        inputs={"X": [label]},
        outputs={"Out": [out]},
        attrs={"scale": 1.0 - epsilon, "bias": epsilon / num_classes,
               "bias_after_scale": True},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": paddings, "pad_value": pad_value})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"paddings": paddings, "mode": mode, "pad_value": pad_value})
    return out


def resize_nearest(input, out_shape=None, scale=None, name=None, align_corners=True):
    helper = LayerHelper("nearest_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"scale": float(scale or 0.0)}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    helper.append_op(type="nearest_interp", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None, align_corners=True):
    helper = LayerHelper("bilinear_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"scale": float(scale or 0.0)}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    helper.append_op(type="bilinear_interp", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"max_norm": max_norm})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pow", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"factor": factor})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="unfold", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"kernel_sizes": kernel_sizes, "strides": strides,
               "paddings": paddings, "dilations": dilations},
    )
    return out


def continuous_value_model(input, cvm, use_cvm=True):
    """fluid.layers.continuous_value_model (layers/nn.py:13865): CTR show/
    click column transform over cvm op (operators/cvm_op.cc)."""
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cvm", inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]}, attrs={"use_cvm": use_cvm})
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """fluid.layers.data_norm (layers/nn.py:3195): global normalization from
    running BatchSize/BatchSum/BatchSquareSum stats (operators/data_norm_op.cc).
    The three stats are trainable params whose "grads" carry the batch deltas
    (see ops/ctr.py data_norm_grad)."""
    helper = LayerHelper("data_norm", param_attr=param_attr, act=act, name=name)
    c = input.shape[-1]
    dtype = "float32"
    from ..framework.param_attr import ParamAttr

    batch_size = helper.create_parameter(
        ParamAttr(name=name + ".batch_size" if name else None,
                  initializer=ConstantInitializer(1e4)),
        shape=[c], dtype=dtype)
    batch_sum = helper.create_parameter(
        ParamAttr(name=name + ".batch_sum" if name else None,
                  initializer=ConstantInitializer(0.0)),
        shape=[c], dtype=dtype)
    batch_square_sum = helper.create_parameter(
        ParamAttr(name=name + ".batch_square_sum" if name else None,
                  initializer=ConstantInitializer(1e4)),
        shape=[c], dtype=dtype)
    inputs = {"X": [input], "BatchSize": [batch_size],
              "BatchSum": [batch_sum], "BatchSquareSum": [batch_square_sum]}
    attrs = {"epsilon": epsilon, "data_layout": data_layout,
             "slot_dim": slot_dim, "sync_stats": sync_stats,
             "summary_decay_rate": summary_decay_rate,
             "enable_scale_and_shift": enable_scale_and_shift}
    if enable_scale_and_shift:
        # distinct ParamAttr per param: create_parameter assigns attr.name in
        # place, so sharing one instance would alias scale onto bias
        import copy as _copy

        scale_w = helper.create_parameter(
            _copy.copy(param_attr), shape=[c], dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        bias = helper.create_parameter(
            _copy.copy(param_attr), shape=[c], dtype=dtype, is_bias=True)
        inputs["scale_w"] = [scale_w]
        inputs["bias"] = [bias]
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    scales = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(type="data_norm", inputs=inputs,
                     outputs={"Y": [out], "Means": [means], "Scales": [scales]},
                     attrs=attrs)
    return helper.append_activation(out)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """fluid.layers.nce (layers/loss.py:670) over operators/nce_op.cc.
    ``is_sparse`` is accepted for API parity; grads are dense on TPU (XLA
    scatter-add — the SelectedRows path is a CPU PS concern)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[1]
    num_true = label.shape[1] if len(label.shape) > 1 else 1
    num_neg_samples = 10 if num_neg_samples is None else int(num_neg_samples)
    weight = helper.create_parameter(
        param_attr, shape=[num_total_classes, dim], dtype=input.dtype)
    bias = None
    if bias_attr is not False:
        bias = helper.create_parameter(
            bias_attr, shape=[num_total_classes, 1], dtype=input.dtype,
            is_bias=True)
    sampler_idx = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}[sampler]
    inputs = {"Input": [input], "Label": [label], "Weight": [weight]}
    if bias is not None:
        inputs["Bias"] = [bias]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    attrs = {"num_total_classes": int(num_total_classes),
             "num_neg_samples": num_neg_samples, "seed": seed,
             "sampler": sampler_idx, "is_sparse": is_sparse}
    if custom_dist is not None:
        from ..framework.initializer import NumpyArrayInitializer
        from ..framework.param_attr import ParamAttr
        import numpy as _np

        probs = helper.create_parameter(
            ParamAttr(name=(name + ".dist_probs") if name else None,
                      initializer=NumpyArrayInitializer(
                          _np.asarray(custom_dist, dtype="float32")),
                      trainable=False),
            shape=[num_total_classes], dtype="float32")
        inputs["CustomDistProbs"] = [probs]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True)
    sample_labels = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    helper.append_op(type="nce", inputs=inputs,
                     outputs={"Cost": [cost], "SampleLogits": [sample_logits],
                              "SampleLabels": [sample_labels]},
                     attrs=attrs)
    return cost


def sampled_softmax_with_cross_entropy(logits, label, num_samples, num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None, seed=0):
    """fluid.layers.sampled_softmax_with_cross_entropy (layers/loss.py:1050):
    sample_logits + softmax_with_cross_entropy over the sampled columns."""
    helper = LayerHelper("sample_logits")
    samples = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    probabilities = helper.create_variable_for_type_inference(
        logits.dtype, stop_gradient=True)
    sampled_logits = helper.create_variable_for_type_inference(logits.dtype)
    sampled_label = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    inputs = {"Logits": [logits], "Labels": [label]}
    if use_customized_samples:
        inputs["CustomizedSamples"] = [customized_samples]
        inputs["CustomizedProbabilities"] = [customized_probabilities]
    helper.append_op(
        type="sample_logits", inputs=inputs,
        outputs={"Samples": [samples], "Probabilities": [probabilities],
                 "SampledLogits": [sampled_logits],
                 "SampledLabels": [sampled_label]},
        attrs={"num_samples": int(num_samples),
               "use_customized_samples": use_customized_samples,
               "remove_accidental_hits": remove_accidental_hits,
               "seed": seed})
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [sampled_logits], "Label": [sampled_label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={"soft_label": False, "ignore_index": -100,
               "numeric_stable_mode": False})
    return loss


def shuffle_batch(x, seed=None):
    """fluid.contrib.layers.shuffle_batch (contrib/layers/nn.py:761)."""
    helper = LayerHelper("shuffle_batch")
    out = helper.create_variable_for_type_inference(x.dtype)
    shuffle_idx = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    seed_out = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    inputs = {"X": [x]}
    attrs = {}
    if seed is not None and not isinstance(seed, int):
        inputs["Seed"] = [seed]
    elif seed is not None:
        attrs["startup_seed"] = int(seed)
    helper.append_op(type="shuffle_batch", inputs=inputs,
                     outputs={"Out": [out], "ShuffleIdx": [shuffle_idx],
                              "SeedOut": [seed_out]},
                     attrs=attrs)
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """fluid.layers.py_func (layers/nn.py:13375): run arbitrary Python
    between device segments (host-op). backward_func is accepted for API
    parity; py_func outputs are treated as non-differentiable here (the
    dominant reference use: metrics/logging/data munging)."""
    from ..ops.misc_extra import register_py_func

    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    handle = register_py_func(func)
    helper.append_op(type="py_func", inputs={"X": list(xs)},
                     outputs={"Out": list(outs)},
                     attrs={"forward_callable_id": handle})
    return out
