"""Layer-function API — parity with python/paddle/fluid/layers/."""
from . import math_op_patch  # noqa: F401
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from . import nn  # noqa: F401
from . import tensor  # noqa: F401
from . import rnn  # noqa: F401
from .rnn import lstm, gru, beam_search, beam_search_decode  # noqa: F401
from . import sequence  # noqa: F401
from .sequence import *  # noqa: F401,F403
from . import detection  # noqa: F401
from .detection import *  # noqa: F401,F403
from . import collective  # noqa: F401
from . import control_flow  # noqa: F401
from .control_flow import *  # noqa: F401,F403
from . import extras  # noqa: F401
from .extras import *  # noqa: F401,F403
from . import rnn_api  # noqa: F401
from .rnn_api import *  # noqa: F401,F403
from . import ssd  # noqa: F401
from .ssd import *  # noqa: F401,F403
from .learning_rate_scheduler import (  # noqa: F401
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    linear_lr_warmup,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
