"""Auto-generated + composite layer functions closing the rest of the
fluid.layers surface.

The reference generates most of its thin layer functions from OpProtos
(python/paddle/fluid/layers/ops.py generate_layer_fn / layer_function_
generator.py); :func:`generate_layer_fn` here is the same idea over this
framework's OpSpec registry: one declarative row per op -> a layer function
with named args mapped to input slots and attrs. Composites (image_resize,
dice/npair/rank losses, has_inf/nan, step counters...) are hand-written
below.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable, default_main_program

__all__: List[str] = ["generate_layer_fn"]


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def generate_layer_fn(op_type: str, in_slots: Sequence[str],
                      out_slots: Sequence[str],
                      attr_defaults: Optional[Dict] = None,
                      out_dtypes: Optional[Dict[str, str]] = None,
                      n_ret: Optional[int] = None, name: str = None):
    """Build a thin layer fn for a registered op: positional/keyword args
    named after the (lowercased) input slots; remaining kwargs become op
    attrs (layer_function_generator.py capability)."""
    attr_defaults = dict(attr_defaults or {})
    fn_name = name or op_type

    def layer(*args, name=None, **kwargs):
        helper = LayerHelper(fn_name, name=name)
        inputs = {}
        arg_list = list(args)
        for slot in in_slots:
            key = slot.lower()
            if arg_list:
                val = arg_list.pop(0)
            elif key in kwargs:
                val = kwargs.pop(key)
            else:
                val = None
            if val is None:
                continue
            inputs[slot] = list(val) if isinstance(val, (list, tuple)) \
                else [val]
        attrs = dict(attr_defaults)
        attrs.update(kwargs)
        outs = {}
        ret = []
        first_in = next(iter(inputs.values()))[0] if inputs else None
        for slot in out_slots:
            dtype = (out_dtypes or {}).get(
                slot, first_in.dtype if isinstance(first_in, Variable)
                else "float32")
            v = helper.create_variable_for_type_inference(dtype)
            outs[slot] = [v]
            ret.append(v)
        helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                         attrs=attrs)
        keep = n_ret if n_ret is not None else len(ret)
        return ret[0] if keep == 1 else tuple(ret[:keep])

    layer.__name__ = fn_name
    layer.__doc__ = (f"Auto-generated layer for the `{op_type}` op "
                     f"(reference generate_layer_fn parity).")
    return layer


# ---------------------------------------------------------------------------
# table-generated single-op layers (op already registered in ops/)
# ---------------------------------------------------------------------------

_TABLE = [
    # (fn name, op, in slots, out slots, attr defaults, out dtypes, n_ret)
    ("affine_channel", "affine_channel", ["X", "Scale", "Bias"], ["Out"],
     {"data_layout": "NCHW"}, None, 1),
    ("affine_grid", "affine_grid", ["Theta", "OutputShape"], ["Output"],
     {}, None, 1),
    ("multiplex", "multiplex", ["X", "Ids"], ["Out"], {}, None, 1),
    ("row_conv", "row_conv", ["X", "Filter"], ["Out"], {}, None, 1),
    ("add_position_encoding", "add_position_encoding", ["X"], ["Out"],
     {"alpha": 1.0, "beta": 1.0}, None, 1),
    ("space_to_depth", "space_to_depth", ["X"], ["Out"], {}, None, 1),
    ("shuffle_channel", "shuffle_channel", ["X"], ["Out"], {"group": 1},
     None, 1),
    ("teacher_student_sigmoid_loss", "teacher_student_sigmoid_loss",
     ["X", "Label"], ["Y"], {}, None, 1),
    ("bpr_loss", "bpr_loss", ["X", "Label"], ["Loss"], {}, None, 1),
    ("hinge_loss", "hinge_loss", ["Logits", "Labels"], ["Loss"], {}, None, 1),
    ("margin_rank_loss", "margin_rank_loss", ["Label", "Left", "Right"],
     ["Out", "Activated"], {"margin": 0.1}, None, 1),
    ("rank_loss", "rank_loss", ["Label", "Left", "Right"], ["Out"], {},
     None, 1),
    ("log_loss", "log_loss", ["Predicted", "Labels"], ["Loss"],
     {"epsilon": 1e-4}, None, 1),
    ("mean_iou", "mean_iou", ["Predictions", "Labels"],
     ["OutMeanIou", "OutWrong", "OutCorrect"], {}, None, 3),
    ("cos_sim", "cos_sim", ["X", "Y"], ["Out", "XNorm", "YNorm"], {},
     None, 1),
    ("grid_sampler", "grid_sampler", ["X", "Grid"], ["Output"], {}, None, 1),
    ("pixel_shuffle", "pixel_shuffle", ["X"], ["Out"],
     {"upscale_factor": 1}, None, 1),
    ("lod_reset", "lod_reset", ["X", "Y"], ["Out"], {}, None, 1),
    ("lod_append", "lod_reset", ["X", "Y"], ["Out"], {}, None, 1),
    ("sequence_reshape", "sequence_reshape", ["X"], ["Out"],
     {"new_dim": 1}, None, 1),
    ("sequence_scatter", "sequence_scatter", ["X", "Ids", "Updates"],
     ["Out"], {}, None, 1),
    ("scatter_nd_add", "scatter_nd_add", ["X", "Index", "Updates"],
     ["Out"], {}, None, 1),
    ("unbind", "unbind", ["X"], ["Out"], {}, None, 1),
    ("pool3d", "pool3d", ["X"], ["Out"], {"pooling_type": "max"}, None, 1),
    ("conv3d_transpose_op", "conv3d_transpose", ["Input", "Filter"],
     ["Output"], {}, None, 1),
    ("deformable_conv", "deformable_conv",
     ["Input", "Offset", "Mask", "Filter"], ["Output"],
     {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
      "groups": 1, "deformable_groups": 1, "im2col_step": 1}, None, 1),
    ("prroi_pool", "prroi_pool", ["X", "ROIs"], ["Out"],
     {"pooled_height": 1, "pooled_width": 1, "spatial_scale": 1.0},
     None, 1),
    ("psroi_pool", "psroi_pool", ["X", "ROIs"], ["Out"],
     {"spatial_scale": 1.0}, None, 1),
    ("polygon_box_transform", "polygon_box_transform", ["Input"],
     ["Output"], {}, None, 1),
    ("box_decoder_and_assign", "box_decoder_and_assign",
     ["PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"],
     ["DecodeBox", "OutputAssignBox"], {"box_clip": 4.135}, None, 2),
    ("retinanet_target_assign", "retinanet_target_assign",
     ["Anchor", "GtBoxes", "GtLabels"],
     ["TargetLabel", "TargetBBox", "BBoxInsideWeight", "ForegroundNumber"],
     {"positive_overlap": 0.5, "negative_overlap": 0.4},
     {"TargetLabel": "int32", "ForegroundNumber": "int32"}, 4),
    ("brelu", "brelu", ["X"], ["Out"], {"t_min": 0.0, "t_max": 24.0},
     None, 1),
    ("soft_relu", "soft_relu", ["X"], ["Out"], {"threshold": 40.0},
     None, 1),
    ("selu", "selu", ["X"], ["Out"], {}, None, 1),
    ("stanh", "stanh", ["X"], ["Out"],
     {"scale_a": 0.67, "scale_b": 1.7159}, None, 1),
    ("maxout", "maxout", ["X"], ["Out"], {"groups": 1}, None, 1),
    ("sampling_id", "sampling_id", ["X"], ["Out"], {},
     {"Out": "int64"}, 1),
    ("similarity_focus", "similarity_focus", ["X"], ["Out"], {}, None, 1),
    ("temporal_shift", "temporal_shift", ["X"], ["Out"],
     {"seg_num": 1, "shift_ratio": 0.25}, None, 1),
    ("uniform_random_batch_size_like", "uniform_random_batch_size_like",
     ["Input"], ["Out"], {"shape": [], "min": -1.0, "max": 1.0}, None, 1),
    ("gaussian_random_batch_size_like", "gaussian_random_batch_size_like",
     ["Input"], ["Out"], {"shape": [], "mean": 0.0, "std": 1.0}, None, 1),
    ("inplace_abn", "inplace_abn",
     ["X", "Scale", "Bias", "Mean", "Variance"],
     ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
     {}, None, 1),
    ("gather_tree", "gather_tree", ["Ids", "Parents"], ["Out"], {},
     {"Out": "int64"}, 1),
    ("shard_index_layer", "shard_index", ["X"], ["Out"],
     {"ignore_value": -1}, {"Out": "int64"}, 1),
    ("random_crop", "random_crop", ["X"], ["Out"], {"shape": []}, None, 1),
    ("tensor_array_to_tensor", "tensor_array_to_tensor", ["X"],
     ["Out"], {"axis": 0, "use_stack": False}, None, 1),
    ("edit_distance", "edit_distance",
     ["Hyps", "Refs", "HypsLength", "RefsLength"],
     ["Out", "SequenceNum"], {"normalized": True},
     {"SequenceNum": "int64"}, 2),
]

import sys as _sys

_mod = _sys.modules[__name__]
from ..framework.registry import has_op as _has_op
from ..framework.executor import _HOST_OPS as _HOST

for _row in _TABLE:
    _fn_name, _op, _ins, _outs, _attrs, _odt, _n = _row
    if not (_has_op(_op) or _op in _HOST):
        continue  # table rows are aspirational only when the op exists
    setattr(_mod, _fn_name,
            generate_layer_fn(_op, _ins, _outs, _attrs, _odt, _n,
                              name=_fn_name))
    __all__.append(_fn_name)


# ---------------------------------------------------------------------------
# composites
# ---------------------------------------------------------------------------


@_export
def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    """fluid.layers.image_resize (nn.py): dispatch over the interp ops."""
    op_map = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
              "BICUBIC": "bicubic_interp", "TRILINEAR": "trilinear_interp",
              "LINEAR": "linear_interp"}
    op_type = op_map[resample.upper()]
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if out_shape is not None:
        nd = len(out_shape)
        names = {1: ["out_w"], 2: ["out_h", "out_w"],
                 3: ["out_d", "out_h", "out_w"]}[nd]
        for n, v in zip(names, out_shape):
            attrs[n] = int(v)
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


@_export
def resize_linear(input, out_shape=None, scale=None, name=None,
                  align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "LINEAR",
                        align_corners=align_corners, align_mode=align_mode)


@_export
def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "TRILINEAR",
                        align_corners=align_corners, align_mode=align_mode)


@_export
def resize_bicubic(input, out_shape=None, scale=None, name=None,
                   align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BICUBIC",
                        align_corners=align_corners, align_mode=align_mode)


@_export
def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len (static shapes)."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    return image_resize(input, [oh, ow], resample=resample)


@_export
def dice_loss(input, label, epsilon=1e-5):
    """fluid.layers.dice_loss (nn.py): 1 - 2|X∩Y| / (|X|+|Y|)."""
    from .tensor import cast, reduce_mean, reduce_sum

    label = cast(label, input.dtype)
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dims)
    denom = reduce_sum(input, dim=reduce_dims) \
        + reduce_sum(label, dim=reduce_dims)
    dice_score = 1 - inse * 2 / (denom + epsilon)
    return reduce_mean(dice_score)


@_export
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """fluid.layers.npair_loss (nn.py): cross-entropy over anchor-positive
    similarity + L2 on the embeddings."""
    from .nn import matmul, softmax_with_cross_entropy
    from .tensor import cast, equal, reduce_mean, reduce_sum, reshape, \
        transpose

    l2loss = (reduce_mean(reduce_sum(anchor * anchor, dim=1))
              + reduce_mean(reduce_sum(positive * positive, dim=1))) \
        * l2_reg
    sim = matmul(anchor, positive, transpose_y=True)
    lbl = reshape(labels, [-1, 1])
    tgt = cast(equal(lbl, transpose(lbl, perm=[1, 0])), "float32")
    tgt = tgt / reduce_sum(tgt, dim=1, keep_dim=True)
    ce = softmax_with_cross_entropy(sim, tgt, soft_label=True)
    return reduce_mean(ce) + l2loss


@_export
def has_inf(x):
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="has_inf", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


@_export
def has_nan(x):
    helper = LayerHelper("has_nan")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="has_nan", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


@_export
def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """fluid.layers.autoincreased_step_counter: persistable int64 counter
    incremented once per executor run."""
    helper = LayerHelper("global_step_counter")
    block = helper.main_program.global_block()
    name = counter_name or "@STEP_COUNTER@"
    if name in block.vars:
        counter = block.var(name)
    else:
        counter = block.create_var(name=name, shape=[1], dtype="int64",
                                   persistable=True)
        from ..framework.initializer import ConstantInitializer

        startup = helper.startup_program
        sv = startup.global_block().create_var(
            name=name, shape=[1], dtype="int64", persistable=True)
        ConstantInitializer(float(begin - step))(sv,
                                                 startup.global_block())
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


@_export
def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """fluid.layers.create_parameter."""
    helper = LayerHelper("create_parameter")
    from ..framework.param_attr import ParamAttr

    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape=list(shape), dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


@_export
def sequence_first_step(input, length=None):
    """fluid.layers.sequence_first_step over sequence_pool FIRST."""
    from .sequence import sequence_pool

    return sequence_pool(input, "FIRST", length=length)


@_export
def sequence_last_step(input, length=None):
    from .sequence import sequence_pool

    return sequence_pool(input, "LAST", length=length)


@_export
def sequence_concat(input, name=None):
    """fluid.layers.sequence_concat: concat padded sequences on time."""
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": 1})
    return out


@_export
def crop_tensor(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop_tensor", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    inputs = {"X": [x]}
    if isinstance(shape, Variable):
        inputs["Shape"] = [shape]
    elif shape is not None:
        attrs["shape"] = [int(s) for s in shape]
    if isinstance(offsets, Variable):
        inputs["Offsets"] = [offsets]
    elif offsets is not None:
        attrs["offsets"] = [int(o) for o in offsets]
    helper.append_op(type="crop_tensor", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


crop = crop_tensor
__all__.append("crop")


@_export
def rank(input):
    """fluid.layers.rank — static rank as a constant tensor."""
    from .tensor import fill_constant

    return fill_constant([1], "int32", len(input.shape))


# ---------------------------------------------------------------------------
# wave 2: wrappers over existing ops, param-creating layers, control-flow
# composites, and the documentation/decorator utilities
# ---------------------------------------------------------------------------

_TABLE2 = [
    ("diag", "diag", ["Diagonal"], ["Out"], {}, None, 1),
    ("eye", "eye", [], ["Out"], {"num_rows": 1, "num_columns": -1,
                                 "dtype": "float32"}, {"Out": "float32"}, 1),
    ("is_empty", "is_empty", ["X"], ["Out"], {}, {"Out": "bool"}, 1),
    ("size", "size", ["Input"], ["Out"], {}, {"Out": "int64"}, 1),
    ("sum", "sum", ["X"], ["Out"], {}, None, 1),
    ("reverse", "reverse", ["X"], ["Out"], {"axis": [0]}, None, 1),
    ("lrn", "lrn", ["X"], ["Out"], {"n": 5, "k": 1.0, "alpha": 1e-4,
                                    "beta": 0.75}, None, 1),
    ("scatter_nd", "scatter_nd", ["Index", "Updates"], ["Out"],
     {"shape": []}, None, 1),
    ("sequence_expand", "sequence_expand", ["X", "Y"], ["Out"],
     {"ref_level": -1}, None, 1),
    ("unique", "unique", ["X"], ["Out", "Index"], {},
     {"Index": "int64"}, 2),
    ("unique_with_counts", "unique_with_counts", ["X"],
     ["Out", "Index", "Count"], {}, {"Index": "int64", "Count": "int64"}, 3),
    ("elementwise_floordiv", "elementwise_floordiv", ["X", "Y"], ["Out"],
     {"axis": -1}, None, 1),
    ("pad_constant_like", "pad_constant_like", ["X", "Y"], ["Out"],
     {"pad_value": 0.0}, None, 1),
    ("im2sequence", "im2sequence", ["X"], ["Out"],
     {"kernels": [1, 1], "strides": [1, 1], "paddings": [0, 0, 0, 0]},
     None, 1),
    ("fsp_matrix", "fsp", ["X", "Y"], ["Out"], {}, None, 1),
    ("hash", "hash", ["X"], ["Out"], {"num_hash": 1, "mod_by": 1},
     {"Out": "int64"}, 1),
    ("filter_by_instag", "filter_by_instag",
     ["Ins", "Ins_tag", "Filter_tag"], ["Out", "LossWeight", "IndexMap"],
     {"is_lod": True}, {"IndexMap": "int64"}, 3),
    ("chunk_eval", "chunk_eval", ["Inference", "Label", "SeqLength"],
     ["Precision", "Recall", "F1-Score", "NumInferChunks",
      "NumLabelChunks", "NumCorrectChunks"],
     {"num_chunk_types": 1, "chunk_scheme": "IOB"},
     {"NumInferChunks": "int64", "NumLabelChunks": "int64",
      "NumCorrectChunks": "int64"}, 6),
    ("get_tensor_from_selected_rows", "get_tensor_from_selected_rows",
     ["X"], ["Out"], {}, None, 1),
    ("merge_selected_rows", "merge_selected_rows", ["X"], ["Out"], {},
     None, 1),
    ("locality_aware_nms", "locality_aware_nms", ["BBoxes", "Scores"],
     ["Out"],
     {"score_threshold": 0.0, "nms_top_k": 400, "keep_top_k": 100,
      "nms_threshold": 0.3, "background_label": -1},
     None, 1),
]

for _row in _TABLE2:
    _fn_name, _op, _ins, _outs, _attrs, _odt, _n = _row
    if not (_has_op(_op) or _op in _HOST):
        continue
    setattr(_mod, _fn_name,
            generate_layer_fn(_op, _ins, _outs, _attrs, _odt, _n,
                              name=_fn_name))
    __all__.append(_fn_name)

# conv3d_transpose / shard_index reference-named entry points
conv3d_transpose = generate_layer_fn(
    "conv3d_transpose", ["Input", "Filter"], ["Output"],
    {"strides": [1, 1, 1], "paddings": [0, 0, 0], "dilations": [1, 1, 1],
     "groups": 1}, None, 1, name="conv3d_transpose")
shard_index = generate_layer_fn(
    "shard_index", ["X"], ["Out"], {"ignore_value": -1}, {"Out": "int64"},
    1, name="shard_index")
__all__ += ["conv3d_transpose", "shard_index"]


@_export
def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ksize = pool_size if isinstance(pool_size, (list, tuple)) \
        else [pool_size] * 3
    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": list(ksize),
                            "adaptive": True})
    return out


@_export
def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """fluid.layers.spectral_norm — creates the U/V iteration buffers."""
    from ..framework.initializer import NormalInitializer
    from ..framework.param_attr import ParamAttr

    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w = int(np.prod(weight.shape)) // h
    u = helper.create_parameter(
        ParamAttr(name=None, initializer=NormalInitializer(0.0, 1.0),
                  trainable=False), shape=[h], dtype="float32")
    v = helper.create_parameter(
        ParamAttr(name=None, initializer=NormalInitializer(0.0, 1.0),
                  trainable=False), shape=[w], dtype="float32")
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op(type="spectral_norm",
                     inputs={"Weight": [weight], "U": [u], "V": [v]},
                     outputs={"Out": [out]},
                     attrs={"dim": dim, "power_iters": power_iters,
                            "eps": eps})
    return out


@_export
def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """fluid.layers.bilinear_tensor_product over the op of the same math
    (einsum bi,kij,bj->bk + bias)."""
    helper = LayerHelper("bilinear_tensor_product", name=name,
                         act=act, bias_attr=bias_attr)
    w = helper.create_parameter(
        param_attr, shape=[size, x.shape[1], y.shape[1]], dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="bilinear_tensor_product",
                     inputs={"X": [x], "Y": [y], "Weight": [w]},
                     outputs={"Out": [out]}, attrs={})
    pre = helper.append_bias_op(out, dim_start=1)
    return helper.append_activation(pre)


@_export
def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """fluid.layers.center_loss — creates the Centers state."""
    from ..framework.initializer import NormalInitializer
    from ..framework.param_attr import ParamAttr
    from .tensor import fill_constant

    helper = LayerHelper("center_loss")
    centers = helper.create_parameter(
        ParamAttr(name=None, initializer=NormalInitializer(0.0, 1.0),
                  trainable=False),
        shape=[num_classes, input.shape[1]], dtype=input.dtype)
    rate = alpha if isinstance(alpha, Variable) \
        else fill_constant([1], "float32", float(alpha))
    loss = helper.create_variable_for_type_inference(input.dtype)
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [rate]},
        outputs={"Loss": [loss], "SampleCenterDiff": [diff],
                 "CentersOut": [centers]},
        attrs={"need_update": bool(update_center)})
    return loss


@_export
def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """fluid.layers.gru_unit — creates recurrent weight/bias params."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    D = size // 3
    acts = {"identity": 0, "sigmoid": 1, "tanh": 2, "relu": 3}
    w = helper.create_parameter(param_attr, shape=[D, 3 * D],
                                dtype=input.dtype)
    ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[1, 3 * D],
                                    dtype=input.dtype, is_bias=True)
        ins["Bias"] = [b]
    gate = helper.create_variable_for_type_inference(input.dtype)
    rhp = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gru_unit", inputs=ins,
                     outputs={"Gate": [gate], "ResetHiddenPrev": [rhp],
                              "Hidden": [out]},
                     attrs={"activation": acts[activation],
                            "gate_activation": acts[gate_activation],
                            "origin_mode": origin_mode})
    return out, rhp, gate


@_export
def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """fluid.layers.lstm_unit: fc([x, h]) -> lstm_unit op."""
    from .nn import fc
    from .tensor import concat

    D = hidden_t_prev.shape[1]
    cat = concat([x_t, hidden_t_prev], axis=1)
    gates = fc(cat, 4 * D, param_attr=param_attr, bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", name=name)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": float(forget_bias)})
    return h, c


@_export
def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """fluid.layers.dynamic_lstm on padded [B, T, 4D] projected input."""
    helper = LayerHelper("lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = size // 4
    w = helper.create_parameter(param_attr, shape=[D, 4 * D], dtype=dtype)
    bwidth = 7 * D if use_peepholes else 4 * D
    b = helper.create_parameter(bias_attr, shape=[1, bwidth], dtype=dtype,
                                is_bias=True)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="lstm", inputs=ins,
                     outputs={"Hidden": [hidden], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return hidden, cell


@_export
def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  cell_clip=None, proj_clip=None):
    """fluid.layers.dynamic_lstmp over the lstmp op."""
    helper = LayerHelper("lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = size // 4
    w = helper.create_parameter(param_attr, shape=[proj_size, 4 * D],
                                dtype=dtype)
    wp = helper.create_parameter(param_attr, shape=[D, proj_size],
                                 dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 4 * D], dtype=dtype,
                                is_bias=True)
    ins = {"Input": [input], "Weight": [w], "ProjWeight": [wp],
           "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="lstmp", inputs=ins,
                     outputs={"Projection": [proj], "Cell": [cell]},
                     attrs={"proj_clip": float(proj_clip or 0.0)})
    return proj, cell


@_export
def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False):
    """fluid.layers.dynamic_gru on padded [B, T, 3D] projected input."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr)
    D = size
    dtype = input.dtype
    w = helper.create_parameter(param_attr, shape=[D, 3 * D], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 3 * D], dtype=dtype,
                                is_bias=True)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    hidden = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gru", inputs=ins,
                     outputs={"Hidden": [hidden]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation,
                            "origin_mode": origin_mode})
    return hidden


@_export
def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """fluid.layers.hsigmoid (default complete-binary-tree coding)."""
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    w = helper.create_parameter(
        param_attr, shape=[num_classes - 1, input.shape[1]],
        dtype=input.dtype)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_classes - 1],
                                    dtype=input.dtype, is_bias=True)
        ins["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="hsigmoid", inputs=ins,
                     outputs={"Out": [out], "PreOut": [pre]},
                     attrs={"num_classes": int(num_classes)})
    return out


@_export
def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """fluid.layers.auc over the streaming auc host op (stat buckets are
    persistable state like the reference's)."""
    from ..framework.initializer import ConstantInitializer
    from ..framework.param_attr import ParamAttr

    helper = LayerHelper("auc")
    stat_pos = helper.create_parameter(
        ParamAttr(name=None, initializer=ConstantInitializer(0.0),
                  trainable=False),
        shape=[num_thresholds + 1], dtype="int64")
    stat_neg = helper.create_parameter(
        ParamAttr(name=None, initializer=ConstantInitializer(0.0),
                  trainable=False),
        shape=[num_thresholds + 1], dtype="int64")
    auc_out = helper.create_variable_for_type_inference("float64")
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"num_thresholds": num_thresholds, "curve": curve})
    return auc_out, auc_out, [stat_pos, stat_neg]


@_export
def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """fluid.layers.ctc_greedy_decoder: argmax -> merge repeats -> strip
    blanks (padded convention: returns decoded [B, T] + lengths)."""
    from .tensor import argmax

    helper = LayerHelper("ctc_align", name=name)
    ids = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int64")
    ins = {"Input": [ids]}
    if input_length is not None:
        ins["InputLength"] = [input_length]
    helper.append_op(type="ctc_align", inputs=ins,
                     outputs={"Output": [out], "OutputLength": [out_len]},
                     attrs={"blank": int(blank), "merge_repeated": True})
    return out, out_len


@_export
def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """fluid.layers.Print over the print host op (forward phase)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"first_n": first_n,
                            "message": message or "",
                            "summarize": summarize})
    return out


@_export
def Assert(cond, data=None, summarize=20, name=None):
    """fluid.layers.Assert over an assert host op."""
    helper = LayerHelper("assert")
    helper.append_op(type="assert",
                     inputs={"Cond": [cond],
                             **({"Data": list(data)} if data else {})},
                     outputs={}, attrs={"summarize": summarize})


@_export
def case(pred_fn_pairs, default=None, name=None):
    """fluid.layers.case: first true predicate wins (built on cond)."""
    from .control_flow import cond as cond_layer

    def build(pairs):
        pred, fn = pairs[0]
        rest = pairs[1:]
        if rest:
            return cond_layer(pred, fn, lambda: build(rest))
        if default is not None:
            return cond_layer(pred, fn, default)
        return cond_layer(pred, fn, fn)

    return build(list(pred_fn_pairs))


@_export
def switch_case(branch_index, branch_fns, default=None, name=None):
    """fluid.layers.switch_case over case()."""
    from .tensor import equal, fill_constant

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    pairs = []
    for idx, fn in items:
        c = fill_constant([1], branch_index.dtype, int(idx))
        pairs.append((equal(branch_index, c), fn))
    return case(pairs, default=default)


# documentation/decorator utilities (layer_function_generator.py surface)
@_export
def autodoc(comment=""):
    def deco(fn):
        fn.__doc__ = (fn.__doc__ or "") + comment
        return fn

    return deco


@_export
def templatedoc(op_type=None):
    def deco(fn):
        return fn

    return deco


@_export
def deprecated(since="", update_to="", reason=""):
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}: {reason} "
                f"{('use ' + update_to) if update_to else ''}",
                DeprecationWarning, stacklevel=2)
            return fn(*a, **k)

        return wrapper

    return deco


@_export
def generate_activation_fn(op_type):
    """layer_function_generator.py:generate_activation_fn parity."""
    return generate_layer_fn(op_type, ["X"], ["Out"], {}, None, 1,
                             name=op_type)



# distribution classes exposed under fluid.layers (reference
# layers/distributions.py re-export)
try:
    from ..distribution import Categorical, MultivariateNormalDiag, \
        Normal, Uniform  # noqa: F401

    __all__ += ["Normal", "Uniform", "Categorical",
                "MultivariateNormalDiag"]
except ImportError:  # pragma: no cover
    pass


@_export
def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """The reference's py_reader is superseded by DataLoader in this build
    (the whole-program jit consumes feeds directly; there is no C++ reader
    queue to attach). Use fluid.DataLoader / Dataset instead."""
    raise NotImplementedError(
        "py_reader is replaced by fluid.DataLoader on this framework "
        "(feeds stream straight into the compiled program); see "
        "reader.py DataLoader or dataset.py for the PaddleRec path")


@_export
def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    raise NotImplementedError(
        "create_py_reader_by_data is replaced by fluid.DataLoader "
        "(see py_reader)")


@_export
def double_buffer(reader, place=None, name=None):
    """Device prefetch is owned by the async dispatch + Dataset prefetch
    queues on this framework; double_buffer is an identity."""
    return reader


@_export
def read_file(reader):
    raise NotImplementedError(
        "file readers are replaced by fluid.DataLoader / Dataset "
        "(reader.py, dataset.py)")


@_export
def reorder_lod_tensor_by_rank(x, rank_table):
    """fluid.layers.reorder_lod_tensor_by_rank: permute batch rows by the
    rank table's index column (padded convention)."""
    from .tensor import gather

    helper = LayerHelper("reorder_by_rank")
    idx = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="slice", inputs={"Input": [rank_table]},
                     outputs={"Out": [idx]},
                     attrs={"axes": [1], "starts": [0], "ends": [1]})
    return gather(x, idx)



@_export
def load(out, file_path, load_as_fp16=False):
    """fluid.layers.load over the load host op."""
    helper = LayerHelper("load")
    helper.append_op(type="load", inputs={},
                     outputs={"Out": [out]},
                     attrs={"file_path": file_path})
    return out



@_export
def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """fluid.layers.retinanet_detection_output (detection.py) — per-level
    decode + cross-level NMS, padded [N, keep_top_k, 6] + counts."""
    helper = LayerHelper("retinanet_detection_output")
    out = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="retinanet_detection_output",
        inputs={"BBoxes": list(bboxes), "Scores": list(scores),
                "Anchors": list(anchors), "ImInfo": [im_info]},
        outputs={"Out": [out], "NmsRoisNum": [num]},
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
               "nms_threshold": float(nms_threshold)})
    return out


@_export
def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """fluid.layers.generate_proposal_labels (detection.py:2598) on padded
    batches; fixed [batch_size_per_im] samples, -1-padded labels."""
    helper = LayerHelper("generate_proposal_labels")
    rois = helper.create_variable_for_type_inference("float32")
    labels = helper.create_variable_for_type_inference("int32")
    tgts = helper.create_variable_for_type_inference("float32")
    iw = helper.create_variable_for_type_inference("float32")
    ow = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "GtBoxes": [gt_boxes]},
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [tgts], "BboxInsideWeights": [iw],
                 "BboxOutsideWeights": [ow]},
        attrs={"batch_size_per_im": int(batch_size_per_im),
               "fg_fraction": float(fg_fraction),
               "fg_thresh": float(fg_thresh),
               "bg_thresh_hi": float(bg_thresh_hi),
               "bg_thresh_lo": float(bg_thresh_lo),
               "bbox_reg_weights": [float(w) for w in bbox_reg_weights],
               "class_nums": int(class_nums or 81),
               "use_random": bool(use_random)})
    return rois, labels, tgts, iw, ow



@_export
def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    """fluid.layers.deformable_roi_pooling over deformable_psroi_pooling
    (fluid signature: trans required, position_sensitive default False;
    PS mode divides channels by pooled_height*pooled_width)."""
    helper = LayerHelper("deformable_roi_pooling", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    cnt = helper.create_variable_for_type_inference("float32")
    gh, gw = (group_size if isinstance(group_size, (list, tuple))
              else (group_size, group_size))
    if position_sensitive:
        output_dim = input.shape[1] // (pooled_height * pooled_width)
        gh, gw = pooled_height, pooled_width
    else:
        output_dim = input.shape[1]
        gh = gw = 1
    ins = {"Input": [input], "ROIs": [rois]}
    if trans is not None and not no_trans:
        ins["Trans"] = [trans]
    helper.append_op(
        type="deformable_psroi_pooling", inputs=ins,
        outputs={"Output": [out], "TopCount": [cnt]},
        attrs={"no_trans": bool(no_trans or trans is None),
               "spatial_scale": float(spatial_scale),
               "output_dim": int(output_dim),
               "group_size": [int(gh), int(gw)],
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "part_size": [int(p) for p in (part_size or
                                              (pooled_height,
                                               pooled_width))],
               "sample_per_part": int(sample_per_part),
               "trans_std": float(trans_std)})
    return out


@_export
def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    helper = LayerHelper("roi_perspective_transform")
    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    mat = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_perspective_transform",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out], "Mask": [mask], "TransformMatrix": [mat]},
        attrs={"transformed_height": int(transformed_height),
               "transformed_width": int(transformed_width),
               "spatial_scale": float(spatial_scale)})
    return out, mask, mat


@_export
def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes=None, resolution=14):
    """fluid.layers.generate_mask_labels (Mask R-CNN targets; host-side
    polygon rasterization like the reference CPU kernel). im_info scales
    the original-image polygons; crowd gts are excluded; masks land in
    their class slice when num_classes is given."""
    helper = LayerHelper("generate_mask_labels")
    mask_rois = helper.create_variable_for_type_inference("float32")
    has_mask = helper.create_variable_for_type_inference("int32")
    mask_int32 = helper.create_variable_for_type_inference("int32")
    ins = {"Rois": [rois], "LabelsInt32": [labels_int32],
           "GtSegms": [gt_segms]}
    if im_info is not None:
        ins["ImInfo"] = [im_info]
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    helper.append_op(
        type="generate_mask_labels", inputs=ins,
        outputs={"MaskRois": [mask_rois], "RoiHasMaskInt32": [has_mask],
                 "MaskInt32": [mask_int32]},
        attrs={"resolution": int(resolution),
               "num_classes": int(num_classes or 1)})
    return mask_rois, has_mask, mask_int32
