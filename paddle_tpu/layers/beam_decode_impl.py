"""BeamSearchDecoder.decode implementation: beam search as ONE compiled
scan (DynamicRNN block) — beams folded into the batch dim, per-step
topk over [beam*vocab], parent-gathered states, gather_tree backtrace.

The reference's BeamSearchDecoder (rnn.py:697) builds the same math from
While + beam_search ops over shrinking LoD batches; this build keeps shapes
static: finished beams are forced to extend only with end_token at zero
added score, so every beam always exists.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..framework.layer_helper import LayerHelper


def _arange_rows(batch_size_ref, n, step):
    """[B*n] int64 tensor: row b*step repeated n times (base offsets for
    flattened [B, n] gathers) — built from ops only (no host shapes)."""
    from .tensor import fill_constant_batch_size_like, reshape, cast
    from . import tensor as T

    # cumsum of a [B, 1] constant gives b+1 per row -> (b)*step
    ones = fill_constant_batch_size_like(batch_size_ref, [-1, 1],
                                         "float32", 1.0)
    helper = LayerHelper("beam_arange")
    csum = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="cumsum", inputs={"X": [ones]},
                     outputs={"Out": [csum]}, attrs={"axis": 0})
    base = T.scale(csum - ones, scale=float(step))        # [B, 1] = b*step
    tiled = T.expand(base, expand_times=[1, n])           # [B, n]
    return cast(reshape(tiled, [-1, 1]), "int64")


def beam_decode(decoder, initial_states, max_step_num, batch_size_ref,
                **kwargs):
    from .control_flow import DynamicRNN
    from .nn import log_softmax, topk
    from .tensor import (cast, concat, elementwise_mod, expand,
                         fill_constant, fill_constant_batch_size_like,
                         gather, reshape, transpose)
    from . import tensor as T

    cell = decoder.cell
    K = decoder.beam_size
    multi_state = isinstance(cell.state_shape[0], (list, tuple))
    states0 = initial_states if isinstance(initial_states, (list, tuple)) \
        else [initial_states]

    # tile every state to [B*K, ...] and bias beam 0's score
    def tile_beams(s):
        e = expand(T.unsqueeze(s, axes=[1]),
                   expand_times=[1, K] + [1] * (len(s.shape) - 1))
        return reshape(e, [-1] + list(s.shape[1:]))

    states_tiled = [tile_beams(s) for s in states0]
    score0_np = np.asarray([[0.0] + [-1e9] * (K - 1)], np.float32)
    from .tensor import assign as assign_layer

    score_row = assign_layer(score0_np)                    # [1, K]
    # tile over the UNtiled batch ref -> [B, K] -> [B*K, 1]
    scores_init = reshape(_expand_to_batch(score_row, states0[0]),
                          [-1, 1])

    start = fill_constant_batch_size_like(
        states_tiled[0], [-1, 1], "int64", decoder.start_token)

    steps = int(max_step_num)
    drive = fill_constant_batch_size_like(
        states_tiled[0], [-1, steps, 1], "float32", 0.0)

    drnn = DynamicRNN()
    with drnn.block():
        drnn.step_input(drive)
        states = [drnn.memory(init=s) for s in states_tiled]
        scores = drnn.memory(init=scores_init)             # [B*K, 1]
        tokens = drnn.memory(init=start)                   # [B*K, 1]
        fin = drnn.memory(shape=[1], value=0.0)            # finished flag

        emb = decoder.embedding_fn(reshape(tokens, [-1]))
        cell_states = states if multi_state else states[0]
        out, new_states = cell.call(emb, cell_states, **kwargs)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        logp = log_softmax(logits)                         # [B*K, V]
        V = logp.shape[-1]
        # finished beams may only extend with end_token at zero added score
        end_mask = assign_layer(
            ((np.arange(V) != decoder.end_token) * -1e9)
            .astype(np.float32).reshape(1, V))
        step_logp = logp * (1.0 - fin) + end_mask * fin
        total = scores + step_logp                          # [B*K, V]
        flat = reshape(total, [-1, K * V])                  # [B, K*V]
        top_s, top_i = topk(flat, k=K)                      # [B, K]
        from .extras import elementwise_floordiv

        parent = elementwise_floordiv(
            cast(top_i, "int64"), fill_constant([1], "int64", V))
        token = elementwise_mod(cast(top_i, "int64"),
                                fill_constant([1], "int64", V))
        # flat gather index = b*K + parent
        base = _arange_rows(flat, K, K)                     # [B*K, 1]
        gidx = reshape(base + reshape(parent, [-1, 1]), [-1])
        new_states_l = new_states if multi_state else [new_states]
        gathered = [gather(s, gidx) for s in new_states_l]
        for s, g in zip(states, gathered):
            drnn.update_memory(s, g)
        new_scores = reshape(top_s, [-1, 1])
        new_tokens = reshape(token, [-1, 1])
        drnn.update_memory(scores, new_scores)
        drnn.update_memory(tokens, new_tokens)
        fin_g = gather(fin, gidx)
        now_end = cast(T.equal(new_tokens, fill_constant(
            [1], "int64", decoder.end_token)), "float32")
        drnn.update_memory(fin, T.elementwise_max(fin_g, now_end))
        drnn.output(new_tokens, reshape(parent, [-1, 1]), new_scores)

    ids_seq, parents_seq, scores_seq = drnn()   # [B*K, T, 1]
    ids_tbk = _to_tbk(ids_seq, K)
    parents_tbk = _to_tbk(parents_seq, K)
    helper = LayerHelper("gather_tree")
    full = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="gather_tree",
                     inputs={"Ids": [ids_tbk], "Parents": [parents_tbk]},
                     outputs={"Out": [full]}, attrs={})
    # [T, B, K] -> [B, K, T]
    final_ids = transpose(full, perm=[1, 2, 0])
    final_scores = _last_bk(scores_seq, K)
    return final_ids, final_scores


def _expand_to_batch(row, batch_ref):
    """Tile a [1, K] constant row to [B, K] using a batch-size-like fill."""
    from .tensor import fill_constant_batch_size_like

    zeros = fill_constant_batch_size_like(batch_ref, [-1, row.shape[1]],
                                          "float32", 0.0)
    return zeros + row


def _to_tbk(seq, K):
    """[B*K, T, 1] -> [T, B, K] (gather_tree layout)."""
    from .tensor import reshape, transpose

    t = seq.shape[1]
    r = reshape(seq, [-1, K, t])                           # [B, K, T]
    return cast_int64(transpose(r, perm=[2, 0, 1]))


def cast_int64(x):
    from .tensor import cast

    return cast(x, "int64")


def _last_bk(scores_seq, K):
    from .sequence import sequence_pool
    from .tensor import reshape

    last = sequence_pool(scores_seq, "LAST")               # [B*K, 1]
    return reshape(last, [-1, K])
