"""LR schedulers — parity with fluid/layers/learning_rate_scheduler.py
(noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup).

Each returns a Variable computed from the global step counter
(@LR_DECAY_COUNTER@, incremented once per executor run) so the whole schedule
lives inside the compiled program."""
from __future__ import annotations

import math

from ..framework.layer_helper import LayerHelper
from . import tensor as tl


def _global_step():
    from ..optimizer import _get_or_create_global_step

    step = _get_or_create_global_step()
    return tl.cast(step, "float32")


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _global_step()
    a = tl.elementwise_pow(step, tl.fill_constant([1], "float32", -0.5))
    b = step * (warmup_steps ** -1.5)
    lr = (d_model ** -0.5) * tl.elementwise_min(a, b)
    return lr * learning_rate if learning_rate != 1.0 else lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="floor", inputs={"X": [div]}, outputs={"Out": [out]})
        div = out
    return tl.elementwise_mul(
        tl.fill_constant([1], "float32", learning_rate),
        tl.elementwise_pow(tl.fill_constant([1], "float32", decay_rate), div),
    )


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="floor", inputs={"X": [div]}, outputs={"Out": [out]})
        div = out
    exponent = tl.scale(div, scale=-decay_rate)
    return tl.scale(tl.exp(exponent), scale=learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        helper = LayerHelper("floor")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="floor", inputs={"X": [div]}, outputs={"Out": [out]})
        div = out
    denom = tl.scale(div, scale=decay_rate, bias=1.0)
    return tl.elementwise_div(tl.fill_constant([1], "float32", learning_rate), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _global_step()
    ds = tl.fill_constant([1], "float32", float(decay_steps))
    capped = tl.elementwise_min(step, ds)
    frac = tl.elementwise_div(capped, ds)
    one_minus = tl.scale(frac, scale=-1.0, bias=1.0)
    poly = tl.elementwise_pow(one_minus, tl.fill_constant([1], "float32", power))
    return tl.scale(poly, scale=learning_rate - end_learning_rate,
                    bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    """Implemented with nested where-selects over the step counter."""
    step = _global_step()
    lr = tl.fill_constant([1], "float32", values[-1])
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        below = tl.less_than(step, tl.fill_constant([1], "float32", float(b)))
        lr = tl.where(below, tl.fill_constant([1], "float32", v), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step()
    epoch_f = tl.scale(step, scale=1.0 / step_each_epoch)
    helper = LayerHelper("floor")
    epoch = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="floor", inputs={"X": [epoch_f]}, outputs={"Out": [epoch]})
    inner = tl.scale(epoch, scale=math.pi / epochs)
    helper2 = LayerHelper("cos")
    cosv = helper2.create_variable_for_type_inference("float32")
    helper2.append_op(type="cos", inputs={"X": [inner]}, outputs={"Out": [cosv]})
    return tl.scale(cosv, scale=0.5 * learning_rate, bias=0.0) + tl.fill_constant(
        [1], "float32", 0.5 * learning_rate
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _global_step()
    if not hasattr(learning_rate, "name"):  # scalar
        learning_rate = tl.fill_constant([1], "float32", float(learning_rate))
    warm = tl.scale(step, scale=(end_lr - start_lr) / float(warmup_steps), bias=start_lr)
    in_warmup = tl.less_than(step, tl.fill_constant([1], "float32", float(warmup_steps)))
    return tl.where(in_warmup, warm, learning_rate)
