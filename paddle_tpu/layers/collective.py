"""Collective layer wrappers — parity with fluid/layers/collective.py
(_c_allreduce/_c_allgather/_c_broadcast/... python wrappers of c_* ops)."""
from __future__ import annotations

from ..framework.layer_helper import LayerHelper


def _c_allreduce(x, out=None, reduce_type="sum", ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_allreduce_" + reduce_type)
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="c_allreduce_" + reduce_type,
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"ring_id": ring_id, "use_calc_stream": use_calc_stream},
    )
    return out


def _c_allgather(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_allgather")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="c_allgather",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"nranks": nranks, "ring_id": ring_id,
               "use_calc_stream": use_calc_stream},
    )
    return out


def _c_reducescatter(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_reducescatter")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="c_reducescatter",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"nranks": nranks, "ring_id": ring_id,
               "use_calc_stream": use_calc_stream},
    )
    return out


def _c_broadcast(x, root=0, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_broadcast")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="c_broadcast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"root": root, "ring_id": ring_id,
               "use_calc_stream": use_calc_stream},
    )
    return out


def _c_sync_calc_stream(x):
    return x


def _c_sync_comm_stream(x, ring_id=0):
    return x


def barrier(ring_id=0):
    helper = LayerHelper("barrier")
    helper.append_op(type="barrier", attrs={"ring_id": ring_id})
