"""RNN cell / decode API — parity with python/paddle/fluid/layers/rnn.py
(RNNCell:58, GRUCell:224, LSTMCell:322, rnn:432, Decoder:584,
BeamSearchDecoder:697, dynamic_decode:1168, DecodeHelper family:1398,
BasicDecoder:1852) on this framework's compiled-scan machinery.

TPU-first translation: the reference drives these with a While op over
shrinking LoD batches; here both `rnn` and `dynamic_decode` build their
per-step block inside :class:`~paddle_tpu.layers.control_flow.DynamicRNN`
(ops/dynamic_rnn.py — ONE lax.scan, fixed batch, masking instead of batch
shrink). Decoding runs a fixed `max_step_num` steps with a carried
`finished` flag; outputs past finish are masked (impute_finished
semantics), which is the static-shape equivalent of the reference's
early-exit While.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable

__all__ = ["RNNCell", "GRUCell", "LSTMCell", "rnn", "Decoder",
           "DecodeHelper", "TrainingHelper", "GreedyEmbeddingHelper",
           "SampleEmbeddingHelper", "BasicDecoder", "dynamic_decode",
           "BeamSearchDecoder"]


class RNNCell:
    """rnn.py:58 — step interface: call(inputs, states) -> (out, states)."""

    def call(self, inputs, states, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    @property
    def state_shape(self):
        raise NotImplementedError

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from .tensor import fill_constant_batch_size_like

        shapes = shape or self.state_shape
        if isinstance(shapes, (list, tuple)) and shapes and \
                isinstance(shapes[0], (list, tuple)):
            return [fill_constant_batch_size_like(
                batch_ref, [-1] + list(s), dtype, init_value)
                for s in shapes]
        return fill_constant_batch_size_like(
            batch_ref, [-1] + list(shapes), dtype, init_value)


class GRUCell(RNNCell):
    """rnn.py:224 — gru_unit step with an input projection to 3H."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation="sigmoid", activation="tanh",
                 dtype="float32", name="GRUCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = gate_activation
        self._act = activation
        self._dtype = dtype
        self._name = name

    def call(self, inputs, states):
        from .extras import gru_unit
        from .nn import fc

        proj = fc(inputs, 3 * self.hidden_size,
                  param_attr=self._param_attr, bias_attr=False,
                  name=self._name + "_proj")
        new_hidden, _, _ = gru_unit(
            proj, states, 3 * self.hidden_size,
            param_attr=self._param_attr, bias_attr=self._bias_attr,
            activation=self._act, gate_activation=self._gate_act)
        return new_hidden, new_hidden

    @property
    def state_shape(self):
        return [self.hidden_size]


class LSTMCell(RNNCell):
    """rnn.py:322 — lstm_unit step; states = [hidden, cell]."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation="sigmoid", activation="tanh",
                 forget_bias=1.0, dtype="float32", name="LSTMCell"):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._forget_bias = forget_bias
        self._name = name

    def call(self, inputs, states):
        from .extras import lstm_unit

        pre_h, pre_c = states
        h, c = lstm_unit(inputs, pre_h, pre_c,
                         forget_bias=self._forget_bias,
                         param_attr=self._param_attr,
                         bias_attr=self._bias_attr,
                         name=self._name)
        return h, [h, c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """rnn.py:432 — unroll `cell` over the time axis via DynamicRNN (one
    compiled scan). Returns (outputs [B, T, ...], final_states)."""
    from .control_flow import DynamicRNN
    from .sequence import sequence_pool
    from .extras import reverse as rev_layer
    from .tensor import transpose

    if time_major:
        inputs = transpose(inputs, perm=[1, 0] +
                           list(range(2, len(inputs.shape))))
    if is_reverse:
        inputs = rev_layer(inputs, axis=[1])

    multi_state = isinstance(cell.state_shape[0], (list, tuple))
    drnn = DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(inputs, length=sequence_length)
        if initial_states is None:
            if multi_state:
                states = [drnn.memory(shape=s, value=0.0)
                          for s in cell.state_shape]
            else:
                states = drnn.memory(shape=cell.state_shape, value=0.0)
        else:
            if multi_state:
                states = [drnn.memory(init=s) for s in initial_states]
            else:
                states = drnn.memory(init=initial_states)
        out, new_states = cell.call(x_t, states, **kwargs)
        if multi_state:
            for s, ns in zip(states, new_states):
                drnn.update_memory(s, ns)
            drnn.output(out, *list(new_states))
        else:
            drnn.update_memory(states, new_states)
            drnn.output(out, new_states)
    results = drnn()
    outputs = results[0]
    state_seqs = results[1:]
    if sequence_length is not None:
        finals = [sequence_pool(s, "LAST", length=sequence_length)
                  for s in state_seqs]
    else:
        finals = [sequence_pool(s, "LAST") for s in state_seqs]
    final_states = finals if multi_state else finals[0]
    if is_reverse:
        outputs = rev_layer(outputs, axis=[1])
    if time_major:
        outputs = transpose(outputs, perm=[1, 0] +
                            list(range(2, len(outputs.shape))))
    return outputs, final_states


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------


class Decoder:
    """rnn.py:584 — initialize/step/finalize protocol."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError


class DecodeHelper:
    """rnn.py:1398 — initialize/sample/next_inputs protocol."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """rnn.py:1467 — teacher forcing: step t consumes inputs[:, t]."""

    def __init__(self, inputs, sequence_length=None, time_major=False):
        from .tensor import transpose

        self.inputs = transpose(inputs, perm=[1, 0] + list(
            range(2, len(inputs.shape)))) if time_major else inputs
        self.sequence_length = sequence_length

    @property
    def max_steps(self):
        return self.inputs.shape[1]


class GreedyEmbeddingHelper(DecodeHelper):
    """rnn.py:1620 — feedback = embedding(argmax(logits))."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = start_tokens  # [B] int64 var
        self.end_token = int(end_token)

    def sample(self, logits):
        from .tensor import argmax

        return argmax(logits, axis=-1)


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """rnn.py:1751 — feedback sampled from softmax(logits)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature

    def sample(self, logits):
        from .extras import sampling_id
        from .nn import softmax
        from .tensor import scale as scale_layer

        if self.temperature is not None:
            logits = scale_layer(logits, scale=1.0 / self.temperature)
        return sampling_id(softmax(logits))


class BasicDecoder(Decoder):
    """rnn.py:1852 — cell + helper (+ output fc)."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """rnn.py:1168 for BasicDecoder: a fixed-length compiled scan with a
    carried `finished` flag (static-shape equivalent of the early-exit
    While; finished steps keep emitting the end token and their outputs
    are maskable via the returned lengths)."""
    from .control_flow import DynamicRNN
    from .tensor import (cast, fill_constant_batch_size_like, reduce_sum,
                         transpose, zeros_like)
    from . import tensor as T

    if not isinstance(decoder, BasicDecoder):
        raise NotImplementedError(
            "dynamic_decode drives BasicDecoder (use BeamSearchDecoder."
            "decode for beam search)")
    helper = decoder.helper
    cell = decoder.cell
    teacher = isinstance(helper, TrainingHelper)
    if teacher:
        steps = helper.max_steps
    else:
        if max_step_num is None:
            raise ValueError("max_step_num is required for free-running "
                             "decode (static shapes)")
        steps = int(max_step_num)

    multi_state = isinstance(cell.state_shape[0], (list, tuple))

    # the scan driver: teacher forcing steps over the target sequence;
    # free-running decode steps over a dummy time axis and feeds back
    # sampled embeddings through a memory
    if teacher:
        drive = helper.inputs
    else:
        first = helper.embedding_fn(helper.start_tokens)   # [B, E]
        drive = fill_constant_batch_size_like(
            first, [-1, steps, 1], "float32", 0.0)

    drnn = DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(
            drive, length=helper.sequence_length if teacher else None)
        if inits is not None:
            states = [drnn.memory(init=s) for s in inits] if multi_state \
                else drnn.memory(init=inits)
        else:
            if multi_state:
                states = [drnn.memory(shape=s, value=0.0)
                          for s in cell.state_shape]
            else:
                states = drnn.memory(shape=cell.state_shape, value=0.0)
        if teacher:
            cell_in = x_t
        else:
            cell_in = drnn.memory(init=first)
            fin_prev = drnn.memory(shape=[1], value=0.0)   # finished flag
        out, new_states = cell.call(cell_in, states, **kwargs)
        logits = decoder.output_fn(out) if decoder.output_fn is not None \
            else out
        if multi_state:
            for s, ns in zip(states, new_states):
                drnn.update_memory(s, ns)
        else:
            drnn.update_memory(states, new_states)
        if teacher:
            drnn.output(logits)
        else:
            sample_ids = helper.sample(logits)             # [B]
            next_in = helper.embedding_fn(sample_ids)
            drnn.update_memory(cell_in, next_in)
            from .tensor import equal as eq_layer, fill_constant

            endv = fill_constant([1], sample_ids.dtype, helper.end_token)
            now_end = cast(eq_layer(T.reshape(sample_ids, [-1, 1]), endv),
                           "float32")
            fin = T.elementwise_max(fin_prev, now_end) if hasattr(
                T, "elementwise_max") else fin_prev + now_end - \
                fin_prev * now_end
            drnn.update_memory(fin_prev, fin)
            drnn.output(logits, T.reshape(
                cast(T.reshape(sample_ids, [-1, 1]), "int64"), [-1, 1]),
                fin_prev)
    results = drnn()
    if teacher:
        outputs = results if isinstance(results, Variable) else results[0]
        lengths = helper.sequence_length
        ret_extra = None
    else:
        outputs, ids_seq, fin_seq = results
        # length = steps until (and including) the first end token
        alive = 1.0 - T.reshape(fin_seq, [-1, steps])
        lengths = cast(reduce_sum(alive, dim=1), "int64")
        ret_extra = ids_seq
    if output_time_major:
        outputs = transpose(outputs, perm=[1, 0] + list(
            range(2, len(outputs.shape))))
    if return_length:
        return (outputs, ret_extra, lengths) if ret_extra is not None \
            else (outputs, lengths)
    return (outputs, ret_extra) if ret_extra is not None else outputs


class BeamSearchDecoder(Decoder):
    """rnn.py:697 — beam-search decoding over a cell. Implemented
    functionally with the beam folded into the batch dim and a compiled
    per-step topk; gather_tree reconstructs the predecessor chains
    (operators/gather_tree_op.cc)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def decode(self, initial_states, max_step_num, batch_size_ref,
               **kwargs):
        """Run beam search for max_step_num steps; returns
        (token ids [B, beam, T], per-beam scores [B, beam])."""
        from .beam_decode_impl import beam_decode

        return beam_decode(self, initial_states, int(max_step_num),
                           batch_size_ref, **kwargs)
