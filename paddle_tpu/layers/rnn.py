"""RNN layers — fluid/layers/rnn.py surface subset (lstm, gru) over the
scan-based fused ops in ops/rnn.py."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..ops.rnn import lstm_blob_size

__all__ = ["lstm", "gru"]


def lstm(input, init_h, init_c, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, is_test=False,
         name=None, default_initializer=None, seed=-1,
         sequence_length=None, param_attr=None):
    """fluid.layers.lstm (cudnn path, fluid/layers/rnn.py).

    input: [B, T, D]; init_h/init_c: [num_layers, B, hidden_size].
    Returns (out [B,T,H], last_h, last_c).
    """
    if is_bidirec:
        raise NotImplementedError("bidirectional lstm: pending")
    assert hidden_size is not None
    helper = LayerHelper("lstm", param_attr=param_attr, name=name)
    d = input.shape[-1]
    blob = lstm_blob_size(d, hidden_size, num_layers)
    from ..framework.initializer import UniformInitializer
    import math
    k = 1.0 / math.sqrt(hidden_size)
    w = helper.create_parameter(
        param_attr, shape=[blob], dtype=input.dtype,
        default_initializer=default_initializer or UniformInitializer(-k, k))
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Input": [input], "W": [w], "InitH": [init_h], "InitC": [init_c]}
    if sequence_length is not None:
        inputs["SequenceLength"] = [sequence_length]
    helper.append_op(
        type="cudnn_lstm", inputs=inputs,
        outputs={"Out": [out.name], "LastH": [last_h.name],
                 "LastC": [last_c.name]},
        attrs={"num_layers": num_layers, "hidden_size": hidden_size,
               "dropout_prob": dropout_prob, "is_test": is_test})
    return out, last_h, last_c


def gru(input, hidden_size: int, init_h=None, sequence_length=None,
        param_attr=None, bias_attr=None, name=None):
    """Batch-major GRU layer over the fused_gru op (gru_op.cc gate layout)."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    d = input.shape[-1]
    from ..framework.initializer import UniformInitializer
    import math
    k = 1.0 / math.sqrt(hidden_size)
    init = UniformInitializer(-k, k)
    wx = helper.create_parameter(param_attr, shape=[d, 3 * hidden_size],
                                 dtype=input.dtype, default_initializer=init)
    wh = helper.create_parameter(param_attr, shape=[hidden_size, 3 * hidden_size],
                                 dtype=input.dtype, default_initializer=init)
    b = helper.create_parameter(bias_attr, shape=[3 * hidden_size],
                                dtype=input.dtype, is_bias=True)
    if init_h is None:
        raise ValueError("gru requires init_h (shape [B, hidden_size])")
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Input": [input], "WeightX": [wx], "WeightH": [wh], "Bias": [b],
              "InitH": [init_h]}
    if sequence_length is not None:
        inputs["SequenceLength"] = [sequence_length]
    helper.append_op(
        type="fused_gru", inputs=inputs,
        outputs={"Out": [out.name], "LastH": [last_h.name]},
        attrs={})
    return out, last_h
