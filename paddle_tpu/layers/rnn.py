"""RNN layers — fluid/layers/rnn.py surface subset (lstm, gru) over the
scan-based fused ops in ops/rnn.py."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..ops.rnn import lstm_blob_size

__all__ = ["lstm", "gru", "beam_search", "beam_search_decode"]


def _derived_attr(attr, suffix):
    """A layer with several parameters must not reuse one explicit
    ParamAttr name for all of them; derive '<name>.<suffix>' per param."""
    from ..framework.param_attr import ParamAttr

    if attr is None or not isinstance(attr, (str, ParamAttr)):
        return attr
    attr = ParamAttr._to_attr(attr)
    if attr.name is None:
        return attr
    import copy

    out = copy.copy(attr)
    out.name = f"{attr.name}.{suffix}"
    return out


def lstm(input, init_h, init_c, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, is_test=False,
         name=None, default_initializer=None, seed=-1,
         sequence_length=None, param_attr=None):
    """fluid.layers.lstm (cudnn path, fluid/layers/rnn.py).

    input: [B, T, D]; init_h/init_c: [num_layers * num_directions, B,
    hidden_size] (directions = 2 when is_bidirec, fwd state before bwd per
    layer). Returns (out [B, T, H*directions], last_h, last_c).
    """
    assert hidden_size is not None
    helper = LayerHelper("lstm", param_attr=param_attr, name=name)
    d = input.shape[-1]
    blob = lstm_blob_size(d, hidden_size, num_layers,
                          num_directions=2 if is_bidirec else 1)
    from ..framework.initializer import UniformInitializer
    import math
    k = 1.0 / math.sqrt(hidden_size)
    w = helper.create_parameter(
        param_attr, shape=[blob], dtype=input.dtype,
        default_initializer=default_initializer or UniformInitializer(-k, k))
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Input": [input], "W": [w], "InitH": [init_h], "InitC": [init_c]}
    if sequence_length is not None:
        inputs["SequenceLength"] = [sequence_length]
    helper.append_op(
        type="cudnn_lstm", inputs=inputs,
        outputs={"Out": [out.name], "LastH": [last_h.name],
                 "LastC": [last_c.name]},
        attrs={"num_layers": num_layers, "hidden_size": hidden_size,
               "dropout_prob": dropout_prob, "is_test": is_test,
               "is_bidirec": is_bidirec})
    return out, last_h, last_c


def gru(input, hidden_size: int, init_h=None, sequence_length=None,
        param_attr=None, bias_attr=None, name=None):
    """Batch-major GRU layer over the fused_gru op (gru_op.cc gate layout)."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    d = input.shape[-1]
    from ..framework.initializer import UniformInitializer
    import math
    k = 1.0 / math.sqrt(hidden_size)
    init = UniformInitializer(-k, k)
    wx = helper.create_parameter(_derived_attr(param_attr, "wx"),
                                 shape=[d, 3 * hidden_size],
                                 dtype=input.dtype, default_initializer=init)
    wh = helper.create_parameter(_derived_attr(param_attr, "wh"),
                                 shape=[hidden_size, 3 * hidden_size],
                                 dtype=input.dtype, default_initializer=init)
    b = helper.create_parameter(_derived_attr(bias_attr, "b"),
                                shape=[3 * hidden_size],
                                dtype=input.dtype, is_bias=True)
    if init_h is None:
        raise ValueError("gru requires init_h (shape [B, hidden_size])")
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Input": [input], "WeightX": [wx], "WeightH": [wh], "Bias": [b],
              "InitH": [init_h]}
    if sequence_length is not None:
        inputs["SequenceLength"] = [sequence_length]
    helper.append_op(
        type="fused_gru", inputs=inputs,
        outputs={"Out": [out.name], "LastH": [last_h.name]},
        attrs={})
    return out, last_h


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """fluid.layers.beam_search (reference layers/rnn.py:2880 /
    operators/beam_search_op.cc) — dense TPU formulation; see
    ops/beam_search.py for the state-layout conventions. `ids` is accepted
    for API parity and unused (token ids are implied by the vocab axis)."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference("float32")
    parent_idx = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "scores": [scores]},
        outputs={"selected_ids": [sel_ids.name],
                 "selected_scores": [sel_scores.name],
                 "parent_idx": [parent_idx.name]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level), "is_accumulated": bool(is_accumulated)})
    if return_parent_idx:
        return sel_ids, sel_scores, parent_idx
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, parent_idx=None,
                       name=None):
    """fluid.layers.beam_search_decode (beam_search_decode_op.cc).

    ids/scores/parent_idx are LoDTensorArray vars filled by array_write at
    each decode step; parent_idx is required in the dense formulation (the
    reference recovers parents from LoD instead).
    """
    if parent_idx is None:
        raise ValueError(
            "beam_search_decode requires the parent_idx tensor array "
            "(collect beam_search(..., return_parent_idx=True) outputs)")
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference("int64")
    sent_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores], "ParentIdx": [parent_idx]},
        outputs={"SentenceIds": [sent_ids.name],
                 "SentenceScores": [sent_scores.name]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)})
    return sent_ids, sent_scores
