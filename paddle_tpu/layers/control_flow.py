"""Control-flow layers — parity with fluid/layers/control_flow.py (3,820 LoC:
While:1042, cond, Switch, increment, array ops, less_than wrappers...).

Sub-Blocks are real IR blocks; the executor lowers them to lax.while_loop /
lax.cond (ops/control_flow.py), keeping shapes static as XLA requires.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable, default_main_program

__all__ = ["While", "cond", "while_loop", "Switch", "array_write", "array_read",
           "array_length", "create_array", "increment", "less_than", "equal",
           "DynamicRNN", "StaticRNN", "IfElse", "lod_rank_table", "max_sequence_len",
           "lod_tensor_to_array", "array_to_lod_tensor", "shrink_memory"]


class While:
    """fluid.layers.While — block-style while loop:

        i = fluid.layers.fill_constant([1], 'int64', 0)
        cond_var = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond_var)
        with w.block():
            ...
            fluid.layers.increment(i)
            fluid.layers.assign(fluid.layers.less_than(i, n), cond_var)
    """

    def __init__(self, cond: Variable, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.while_op = while_op

    def __enter__(self):
        prog = default_main_program()
        self.sub_block = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        prog = default_main_program()
        sub_block_idx = prog.current_block_idx
        prog._rollback()
        parent = prog.current_block()
        parent.append_op(
            type="while",
            inputs={"Condition": [self.while_op.cond_var]},
            outputs={},
            attrs={"sub_block": sub_block_idx,
                   "is_test": self.while_op.is_test},
        )
        return True


def while_loop(cond: Callable, body: Callable, loop_vars: List[Variable],
               is_test=False, name=None):
    """fluid.layers.while_loop — functional while (maps onto While + assign)."""
    from . import tensor as tl

    pre_cond = cond(*loop_vars)
    w = While(pre_cond, is_test=is_test, name=name)
    with w.block():
        out_vars = body(*loop_vars)
        if not isinstance(out_vars, (list, tuple)):
            out_vars = [out_vars]
        for lv, ov in zip(loop_vars, out_vars):
            tl.assign(ov, lv)
        tl.assign(cond(*loop_vars), pre_cond)
    return loop_vars


def cond(pred: Variable, true_fn: Callable = None, false_fn: Callable = None,
         name=None):
    """fluid.layers.cond — two-branch conditional built as two sub-Blocks
    lowered to lax.cond."""
    helper = LayerHelper("cond", name=name)
    prog = default_main_program()

    prog._create_block()
    true_ret = true_fn() if true_fn is not None else None
    true_idx = prog.current_block_idx
    prog._rollback()

    prog._create_block()
    false_ret = false_fn() if false_fn is not None else None
    false_idx = prog.current_block_idx
    prog._rollback()

    def _flatten(ret):
        if ret is None:
            return []
        if isinstance(ret, (list, tuple)):
            return list(ret)
        return [ret]

    t_outs = _flatten(true_ret)
    f_outs = _flatten(false_ret)
    if len(t_outs) != len(f_outs):
        raise ValueError("true_fn and false_fn must return the same structure")

    # captured external inputs of both branches become real op inputs so the
    # backward dependency walk sees them and the generic vjp differentiates
    # through lax.cond (reference conditional_block grad analog)
    def _external_reads(idx):
        blk = prog.block(idx)
        produced = set()
        reads = []
        for op_ in blk.ops:
            for n in op_.input_arg_names:
                if n not in produced and n not in reads and n not in blk.vars:
                    reads.append(n)
            produced.update(op_.output_arg_names)
        return reads

    captured = []
    for idx in (true_idx, false_idx):
        for n in _external_reads(idx):
            if n not in captured and n != pred.name:
                captured.append(n)

    outs = [helper.create_variable_for_type_inference(v.dtype) for v in t_outs]
    helper.append_op(
        type="cond",
        inputs={"Cond": [pred], "Input": captured},
        outputs={"Out": outs},
        attrs={
            "true_block": true_idx,
            "false_block": false_idx,
            "true_outs": [v.name for v in t_outs],
            "false_outs": [v.name for v in f_outs],
            "input_names": list(captured),
        },
    )
    if not outs:
        return None
    if len(outs) == 1:
        return outs[0]
    return outs


class Switch:
    """fluid.layers.Switch — sugar over nested cond. Usage:
        with switch.case(cond1): ...
        with switch.default(): ...
    Implemented eagerly over conditional_block ops."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._case_conds: List[Variable] = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        return False

    def case(self, condition):
        self._case_conds.append(condition)
        return _CaseGuard(self, condition)

    def default(self):
        return _CaseGuard(self, None)

    def _none_matched(self) -> Variable:
        """not any(previous case conditions) — real default semantics."""
        from . import tensor as tl

        if not self._case_conds:
            return tl.fill_constant([1], "bool", 1.0)
        acc = self._case_conds[0]
        for c in self._case_conds[1:]:
            acc = tl.logical_or(acc, c)
        return tl.logical_not(acc)


class _CaseGuard:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    def __enter__(self):
        prog = default_main_program()
        self.block = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        prog = default_main_program()
        sub_idx = prog.current_block_idx
        prog._rollback()
        parent = prog.current_block()
        if self.condition is not None:
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [self.condition]},
                outputs={},
                attrs={"sub_block": sub_idx, "is_scalar_condition": True},
            )
        else:
            # default runs only when no prior case matched
            none_matched = self.switch._none_matched()
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [none_matched]},
                outputs={},
                attrs={"sub_block": sub_idx, "is_scalar_condition": True},
            )
        return True


def create_array(dtype):
    from ..framework.core import VarType

    helper = LayerHelper("array")
    return helper.main_program.current_block().create_var(
        name=helper.name, dtype=dtype, type=VarType.LOD_TENSOR_ARRAY
    )


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type="write_to_array",
        inputs={"X": [x], "I": [i]},
        outputs={"Out": [array]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(type="array_length", inputs={"X": [array]}, outputs={"Out": [out]})
    return out


# re-exports used by While conditions
from .tensor import equal, increment, less_than  # noqa: E402,F401


class DynamicRNN:
    """fluid.layers.DynamicRNN (reference control_flow.py:2927) on the
    padded representation: ``step_input`` takes [B, T, ...] sequences (+
    optional per-batch ``length``), the user's block builds one time step,
    and the whole loop compiles to a single ``lax.scan`` via the
    ``dynamic_rnn`` op (ops/dynamic_rnn.py — the reference's rank-table /
    batch-shrink machinery replaced by masking, see that module's docstring).

        drnn = DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sentence, length=seq_len)
            prev = drnn.memory(shape=[H], value=0.0)
            hidden = fluid.layers.fc([word, prev], H, act="tanh")
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        out = drnn()   # [B, T, H], zero past each row's length
    """

    BEFORE_RNN, IN_RNN, AFTER_RNN = range(3)

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = self.BEFORE_RNN
        self._step_outer: List[Variable] = []
        self._step_inner: List[Variable] = []
        self._static_outer: List[Variable] = []
        self._static_inner: List[str] = []
        self._mems: List[Variable] = []
        self._mem_inits: List = []       # Variable | (value, dim)
        self._mem_updates: Dict[str, str] = {}
        self._outputs_inner: List[Variable] = []
        self._length: Variable = None
        self._outer_outputs: List[Variable] = []

    def block(self):
        return _DynamicRNNBlock(self)

    def _assert_in_rnn(self, method):
        if self.status != self.IN_RNN:
            raise ValueError(f"{method} must be called inside drnn.block()")

    def step_input(self, x, level=0, length=None):
        self._assert_in_rnn("step_input")
        if length is not None:
            self._length = length
        prog = default_main_program()
        inner = prog.current_block().create_var(
            name=f"{x.name}@drnn_step",
            shape=[x.shape[0]] + list(x.shape[2:]), dtype=x.dtype)
        self._step_outer.append(x)
        self._step_inner.append(inner)
        return inner

    def static_input(self, x):
        self._assert_in_rnn("static_input")
        prog = default_main_program()
        inner = prog.current_block().create_var(
            name=f"{x.name}@drnn_static", shape=list(x.shape), dtype=x.dtype)
        self._static_outer.append(x)
        self._static_inner.append(inner.name)
        return inner

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        self._assert_in_rnn("memory")
        prog = default_main_program()
        if init is not None:
            mshape = list(init.shape)
            mdtype = init.dtype
            self._mem_inits.append(init)
        else:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            dim = shape[-1] if isinstance(shape, (list, tuple)) else shape
            mshape = [-1, int(dim)]
            mdtype = dtype
            self._mem_inits.append((float(value), int(dim)))
        mem = prog.current_block().create_var(
            name=self.helper.create_variable_for_type_inference(
                mdtype).name + "@drnn_mem",
            shape=mshape, dtype=mdtype)
        self._mems.append(mem)
        return mem

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn("update_memory")
        self._mem_updates[ex_mem.name] = new_mem.name

    def output(self, *outputs):
        self._assert_in_rnn("output")
        self._outputs_inner.extend(outputs)

    def __call__(self):
        if self.status != self.AFTER_RNN:
            raise ValueError("call drnn() after exiting drnn.block()")
        if len(self._outer_outputs) == 1:
            return self._outer_outputs[0]
        return self._outer_outputs


class _DynamicRNNBlock:
    def __init__(self, drnn: DynamicRNN):
        self.drnn = drnn

    def __enter__(self):
        self.drnn.status = DynamicRNN.IN_RNN
        prog = default_main_program()
        self.sub_block = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        d = self.drnn
        if not d._step_inner:
            raise ValueError("DynamicRNN needs at least one step_input")
        if not d._outputs_inner:
            raise ValueError("DynamicRNN needs at least one output")
        prog = default_main_program()
        sub_idx = prog.current_block_idx
        sub_block = prog.current_block()
        prog._rollback()
        parent = prog.current_block()

        # captured = everything the step block reads that lives outside it
        inner_defined = {v.name for v in d._step_inner} \
            | set(d._static_inner) | {m.name for m in d._mems}
        written, read = set(), set()
        for op in sub_block.ops:
            for n in op.input_arg_names:
                if n not in written and n not in inner_defined:
                    read.add(n)
            for n in op.output_arg_names:
                written.add(n)
        def _exists(n):
            try:
                parent._var_recursive(n)
                return True
            except Exception:
                return False

        captured = sorted(n for n in read if _exists(n))

        T = d._step_outer[0].shape[1]
        ins = {"StepIn": [v.name for v in d._step_outer],
               "Captured": captured}
        if d._static_outer:
            ins["Static"] = [v.name for v in d._static_outer]
        var_inits = [m for m in d._mem_inits if isinstance(m, Variable)]
        if var_inits:
            ins["Init"] = [v.name for v in var_inits]
        if d._length is not None:
            ins["Length"] = [d._length.name]

        outs = []
        for ov in d._outputs_inner:
            outer = parent.create_var(
                name=ov.name + "@drnn_out",
                shape=[ov.shape[0], T] + list(ov.shape[1:]), dtype=ov.dtype)
            outs.append(outer)
        d._outer_outputs = outs

        mem_update = []
        for m in d._mems:
            upd = d._mem_updates.get(m.name)
            if upd is None:
                raise ValueError(f"memory {m.name} never update_memory()'d")
            mem_update.append(upd)

        parent.append_op(
            type="dynamic_rnn",
            inputs=ins,
            outputs={"Out": [o.name for o in outs]},
            attrs={
                "sub_block": sub_idx,
                "step_inner": [v.name for v in d._step_inner],
                "static_inner": list(d._static_inner),
                "mem_inner": [m.name for m in d._mems],
                "mem_update": mem_update,
                "mem_init_const": [None if isinstance(m, Variable) else m
                                   for m in d._mem_inits],
                "out_inner": [v.name for v in d._outputs_inner],
                "captured_names": captured,
            },
        )
        d.status = DynamicRNN.AFTER_RNN
        return True


def lod_rank_table(x, level=0, length=None):
    """fluid.layers.lod_rank_table — padded form emits the (index, length)
    table sorted by length desc (ops/dynamic_rnn.py)."""
    helper = LayerHelper("lod_rank_table")
    out = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="lod_rank_table", inputs=ins,
                     outputs={"Out": [out]}, attrs={})
    return out


def max_sequence_len(rank_table):
    helper = LayerHelper("max_sequence_len")
    out = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]}, attrs={})
    return out


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [out]}, attrs={})
    return out


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [out]}, attrs={})
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_rnn_memory")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]}, attrs={})
    return out


class StaticRNN:
    """fluid.layers.StaticRNN (reference control_flow.py:477): fixed-length
    unroll authoring surface. Same step-block design as DynamicRNN, without
    per-row lengths (every sequence runs the full T steps)."""

    def __init__(self, name=None):
        self._drnn = DynamicRNN(name=name)
        self._outputs = []

    def step(self):
        return self._drnn.block()

    def step_input(self, x):
        return self._drnn.step_input(x)

    def step_output(self, o):
        self._drnn.output(o)
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is not None:
            return self._drnn.memory(init=init)
        return self._drnn.memory(shape=shape, value=init_value)

    def update_memory(self, mem, var):
        self._drnn.update_memory(mem, var)

    def __call__(self):
        return self._drnn()


class IfElse:
    """fluid.layers.IfElse (reference control_flow.py:1540): row-routing
    conditional. true_block()/false_block() compute on mask-split rows
    (split_lod_tensor zeroes the other branch's rows — fixed shapes instead
    of the reference's row extraction); __call__ merges per the mask."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper("ifelse", name=name)
        self._in_true = None
        self._splits = {}          # input var name -> (true, false) vars
        self._true_outs: List = []
        self._false_outs: List = []

    class _Branch:
        def __init__(self, owner, is_true):
            self.owner = owner
            self.is_true = is_true

        def __enter__(self):
            self.owner._in_true = self.is_true
            return self

        def __exit__(self, exc_type, exc_val, exc_tb):
            self.owner._in_true = None
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x):
        if self._in_true is None:
            raise ValueError("IfElse.input() must run inside a branch block")
        if x.name not in self._splits:
            t = self.helper.create_variable_for_type_inference(x.dtype)
            f = self.helper.create_variable_for_type_inference(x.dtype)
            self.helper.append_op(
                type="split_lod_tensor",
                inputs={"X": [x], "Mask": [self.cond]},
                outputs={"OutTrue": [t], "OutFalse": [f]}, attrs={})
            self._splits[x.name] = (t, f)
        t, f = self._splits[x.name]
        return t if self._in_true else f

    def output(self, *outs):
        if self._in_true is None:
            raise ValueError("IfElse.output() must run inside a branch block")
        (self._true_outs if self._in_true else self._false_outs).extend(outs)

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError(
                f"IfElse branches produced {len(self._true_outs)} vs "
                f"{len(self._false_outs)} outputs")
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            o = self.helper.create_variable_for_type_inference(t.dtype)
            self.helper.append_op(
                type="merge_lod_tensor",
                inputs={"InTrue": [t], "InFalse": [f],
                        "Mask": [self.cond], "X": [t]},
                outputs={"Out": [o]}, attrs={})
            merged.append(o)
        return merged
