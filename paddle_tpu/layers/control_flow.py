"""Control-flow layers — parity with fluid/layers/control_flow.py (3,820 LoC:
While:1042, cond, Switch, increment, array ops, less_than wrappers...).

Sub-Blocks are real IR blocks; the executor lowers them to lax.while_loop /
lax.cond (ops/control_flow.py), keeping shapes static as XLA requires.
"""
from __future__ import annotations

from typing import Callable, List, Optional

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable, default_main_program

__all__ = ["While", "cond", "while_loop", "Switch", "array_write", "array_read",
           "array_length", "create_array", "increment", "less_than", "equal"]


class While:
    """fluid.layers.While — block-style while loop:

        i = fluid.layers.fill_constant([1], 'int64', 0)
        cond_var = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond_var)
        with w.block():
            ...
            fluid.layers.increment(i)
            fluid.layers.assign(fluid.layers.less_than(i, n), cond_var)
    """

    def __init__(self, cond: Variable, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.while_op = while_op

    def __enter__(self):
        prog = default_main_program()
        self.sub_block = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        prog = default_main_program()
        sub_block_idx = prog.current_block_idx
        prog._rollback()
        parent = prog.current_block()
        parent.append_op(
            type="while",
            inputs={"Condition": [self.while_op.cond_var]},
            outputs={},
            attrs={"sub_block": sub_block_idx,
                   "is_test": self.while_op.is_test},
        )
        return True


def while_loop(cond: Callable, body: Callable, loop_vars: List[Variable],
               is_test=False, name=None):
    """fluid.layers.while_loop — functional while (maps onto While + assign)."""
    from . import tensor as tl

    pre_cond = cond(*loop_vars)
    w = While(pre_cond, is_test=is_test, name=name)
    with w.block():
        out_vars = body(*loop_vars)
        if not isinstance(out_vars, (list, tuple)):
            out_vars = [out_vars]
        for lv, ov in zip(loop_vars, out_vars):
            tl.assign(ov, lv)
        tl.assign(cond(*loop_vars), pre_cond)
    return loop_vars


def cond(pred: Variable, true_fn: Callable = None, false_fn: Callable = None,
         name=None):
    """fluid.layers.cond — two-branch conditional built as two sub-Blocks
    lowered to lax.cond."""
    helper = LayerHelper("cond", name=name)
    prog = default_main_program()

    prog._create_block()
    true_ret = true_fn() if true_fn is not None else None
    true_idx = prog.current_block_idx
    prog._rollback()

    prog._create_block()
    false_ret = false_fn() if false_fn is not None else None
    false_idx = prog.current_block_idx
    prog._rollback()

    def _flatten(ret):
        if ret is None:
            return []
        if isinstance(ret, (list, tuple)):
            return list(ret)
        return [ret]

    t_outs = _flatten(true_ret)
    f_outs = _flatten(false_ret)
    if len(t_outs) != len(f_outs):
        raise ValueError("true_fn and false_fn must return the same structure")

    # captured external inputs of both branches become real op inputs so the
    # backward dependency walk sees them and the generic vjp differentiates
    # through lax.cond (reference conditional_block grad analog)
    def _external_reads(idx):
        blk = prog.block(idx)
        produced = set()
        reads = []
        for op_ in blk.ops:
            for n in op_.input_arg_names:
                if n not in produced and n not in reads and n not in blk.vars:
                    reads.append(n)
            produced.update(op_.output_arg_names)
        return reads

    captured = []
    for idx in (true_idx, false_idx):
        for n in _external_reads(idx):
            if n not in captured and n != pred.name:
                captured.append(n)

    outs = [helper.create_variable_for_type_inference(v.dtype) for v in t_outs]
    helper.append_op(
        type="cond",
        inputs={"Cond": [pred], "Input": captured},
        outputs={"Out": outs},
        attrs={
            "true_block": true_idx,
            "false_block": false_idx,
            "true_outs": [v.name for v in t_outs],
            "false_outs": [v.name for v in f_outs],
            "input_names": list(captured),
        },
    )
    if not outs:
        return None
    if len(outs) == 1:
        return outs[0]
    return outs


class Switch:
    """fluid.layers.Switch — sugar over nested cond. Usage:
        with switch.case(cond1): ...
        with switch.default(): ...
    Implemented eagerly over conditional_block ops."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._case_conds: List[Variable] = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        return False

    def case(self, condition):
        self._case_conds.append(condition)
        return _CaseGuard(self, condition)

    def default(self):
        return _CaseGuard(self, None)

    def _none_matched(self) -> Variable:
        """not any(previous case conditions) — real default semantics."""
        from . import tensor as tl

        if not self._case_conds:
            return tl.fill_constant([1], "bool", 1.0)
        acc = self._case_conds[0]
        for c in self._case_conds[1:]:
            acc = tl.logical_or(acc, c)
        return tl.logical_not(acc)


class _CaseGuard:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    def __enter__(self):
        prog = default_main_program()
        self.block = prog._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        prog = default_main_program()
        sub_idx = prog.current_block_idx
        prog._rollback()
        parent = prog.current_block()
        if self.condition is not None:
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [self.condition]},
                outputs={},
                attrs={"sub_block": sub_idx, "is_scalar_condition": True},
            )
        else:
            # default runs only when no prior case matched
            none_matched = self.switch._none_matched()
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [none_matched]},
                outputs={},
                attrs={"sub_block": sub_idx, "is_scalar_condition": True},
            )
        return True


def create_array(dtype):
    from ..framework.core import VarType

    helper = LayerHelper("array")
    return helper.main_program.current_block().create_var(
        name=helper.name, dtype=dtype, type=VarType.LOD_TENSOR_ARRAY
    )


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type="write_to_array",
        inputs={"X": [x], "I": [i]},
        outputs={"Out": [array]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(type="array_length", inputs={"X": [array]}, outputs={"Out": [out]})
    return out


# re-exports used by While conditions
from .tensor import equal, increment, less_than  # noqa: E402,F401
