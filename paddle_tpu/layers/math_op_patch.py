"""Operator-overload support for static-graph Variables —
parity with python/paddle/fluid/layers/math_op_patch.py."""
from __future__ import annotations

import numpy as np


def binary_op(self, other, op_type, reverse=False):
    from ..framework.layer_helper import LayerHelper
    from ..framework.program import Variable
    from . import tensor as tl

    if not isinstance(other, Variable):
        # scalar fast-paths via scale op
        if np.isscalar(other):
            if op_type == "elementwise_add":
                return tl.scale(self, scale=1.0, bias=float(other))
            if op_type == "elementwise_sub":
                if reverse:
                    return tl.scale(self, scale=-1.0, bias=float(other))
                return tl.scale(self, scale=1.0, bias=-float(other))
            if op_type == "elementwise_mul":
                return tl.scale(self, scale=float(other))
            if op_type == "elementwise_div" and not reverse:
                return tl.scale(self, scale=1.0 / float(other))
        other = tl.fill_constant(
            shape=list(self.shape) if all(d != -1 for d in self.shape) else [1],
            dtype=self.dtype,
            value=float(other),
        )
    x, y = (other, self) if reverse else (self, other)
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out
