"""SSD detection stack — fluid.layers ssd_loss (detection.py:1515),
multi_box_head (:2110), detection_output (:618), composed from this
framework's primitives (prior_box, bipartite_match, target_assign,
mine_hard_examples, box_coder, multiclass_nms2) exactly the way the
reference composes its ops — but with every stage fixed-shape, so a whole
SSD train step compiles to one XLA program.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

from ..framework.layer_helper import LayerHelper

__all__ = ["ssd_loss", "multi_box_head", "detection_output",
           "detection_map"]


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """decode priors with loc deltas, then on-device multiclass NMS.
    loc [B, P, 4]; scores [B, P, C] (softmax applied here like the
    reference); returns [B, keep_top_k, 6] padded rows (+ counts)."""
    from .detection import box_coder
    from .nn import softmax
    from .tensor import transpose
    from .extras import generate_layer_fn

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")      # [B, P, 4]
    cls = transpose(softmax(scores), perm=[0, 2, 1])         # [B, C, P]
    helper = LayerHelper("detection_output")
    out = helper.create_variable_for_type_inference(loc.dtype)
    index = helper.create_variable_for_type_inference("int64")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="multiclass_nms2",
        inputs={"BBoxes": [decoded], "Scores": [cls]},
        outputs={"Out": [out], "Index": [index], "NmsRoisNum": [num]},
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
               "nms_threshold": float(nms_threshold),
               "background_label": int(background_label)})
    if return_index:
        return out, index
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """detection.py:1515 — the multibox loss:
    1. IoU(prior, gt) -> bipartite/per-prediction match per image
    2. hard-negative mining (max_negative)
    3. loc: smooth_l1 on encoded targets over matched priors
    4. conf: softmax CE with matched labels, mined negatives as background
    location [B, P, 4], confidence [B, P, C], gt_box [B, G, 4] (zero rows
    pad), gt_label [B, G, 1] or [B, G]; returns [B, P, 1] weighted loss
    (normalized by matched count like the reference)."""
    from . import tensor as T
    from .detection import box_coder, iou_similarity
    from .nn import softmax_with_cross_entropy, smooth_l1
    from .tensor import cast, reduce_sum, reshape

    helper = LayerHelper("ssd_loss")
    dtype = location.dtype
    C = confidence.shape[-1]
    P = prior_box.shape[0]

    if len(gt_label.shape) == 2:
        gt_label = T.unsqueeze(gt_label, axes=[2])

    # 1. similarity + match (batched dist [B, G, P])
    sim = iou_similarity(gt_box, prior_box)                  # [B, G, P]
    match_idx = helper.create_variable_for_type_inference("int32")
    match_dist = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [sim]},
        outputs={"ColToRowMatchIndices": [match_idx],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type,
               "dist_threshold": float(overlap_threshold)})

    # 2. mined negatives: conf loss as mining signal (reference computes a
    # temporary softmax CE against background for negatives)
    bg = helper.create_variable_for_type_inference("int64")
    from .tensor import fill_constant_batch_size_like

    bg_label = fill_constant_batch_size_like(
        location, [-1, P, 1], "int64", background_label)
    mining_ce = softmax_with_cross_entropy(confidence, bg_label)  # [B,P,1]
    neg_idx = helper.create_variable_for_type_inference("int32")
    upd_idx = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mine_hard_examples",
        inputs={"ClsLoss": [reshape(mining_ce, [-1, P])],
                "MatchIndices": [match_idx], "MatchDist": [match_dist]},
        outputs={"NegIndices": [neg_idx],
                 "UpdatedMatchIndices": [upd_idx]},
        attrs={"neg_pos_ratio": float(neg_pos_ratio),
               "neg_dist_threshold": float(neg_overlap),
               "mining_type": mining_type})

    # 3. targets via target_assign (labels + encoded boxes)
    lbl_t = helper.create_variable_for_type_inference("int64")
    lbl_w = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="target_assign",
        inputs={"X": [gt_label], "MatchIndices": [upd_idx],
                "NegIndices": [neg_idx]},
        outputs={"Out": [lbl_t], "OutWeight": [lbl_w]},
        attrs={"mismatch_value": int(background_label)})

    # assign raw gt rows per prior, then encode against the priors
    box_t = helper.create_variable_for_type_inference(dtype)
    box_w = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="target_assign",
        inputs={"X": [gt_box], "MatchIndices": [upd_idx]},
        outputs={"Out": [box_t], "OutWeight": [box_w]},
        attrs={"mismatch_value": 0})
    enc_t = box_coder(prior_box, prior_box_var, box_t,
                      code_type="encode_center_size")        # [B, P, 4]

    # 4. losses
    conf_loss = softmax_with_cross_entropy(confidence, lbl_t)   # [B, P, 1]
    conf_loss = conf_loss * lbl_w
    loc_flat = smooth_l1(reshape(location, [-1, 4]),
                         reshape(enc_t, [-1, 4]))               # [B*P, 1]
    loc_loss = reshape(loc_flat, [-1, P, 1]) * box_w

    total = conf_loss_weight * conf_loss + loc_loss_weight * loc_loss
    if normalize:
        n_matched = reduce_sum(box_w) + 1e-6
        total = total / n_matched
    return total


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """detection.py:2110 — per-feature-map prior boxes + conv loc/conf
    heads, flattened and concatenated across maps."""
    from . import tensor as T
    from .detection import prior_box as prior_box_layer
    from .nn import conv2d
    from .tensor import concat, reshape, transpose

    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (n_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, inp in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        step_l = [steps[i], steps[i]] if steps else \
            [step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0]
        box, var = prior_box_layer(
            inp, image, min_sizes=[mins] if not isinstance(
                mins, (list, tuple)) else list(mins),
            max_sizes=[maxs] if maxs and not isinstance(
                maxs, (list, tuple)) else (list(maxs) if maxs else None),
            aspect_ratios=list(ar) if isinstance(ar, (list, tuple))
            else [ar],
            variance=list(variance), flip=flip, clip=clip,
            steps=step_l, offset=offset)
        box = reshape(box, [-1, 4])
        var = reshape(var, [-1, 4])
        num_priors = int(box.shape[0]) // (
            int(inp.shape[2]) * int(inp.shape[3]))
        loc = conv2d(inp, num_priors * 4, kernel_size, padding=pad,
                     stride=stride, name=(name or "mbox") + f"_loc{i}")
        conf = conv2d(inp, num_priors * num_classes, kernel_size,
                      padding=pad, stride=stride,
                      name=(name or "mbox") + f"_conf{i}")
        # NCHW -> [B, H*W*num_priors, 4 / C]
        loc = transpose(loc, perm=[0, 2, 3, 1])
        conf = transpose(conf, perm=[0, 2, 3, 1])
        locs.append(reshape(loc, [0, -1, 4]))
        confs.append(reshape(conf, [0, -1, num_classes]))
        boxes_l.append(box)
        vars_l.append(var)

    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    boxes = concat(boxes_l, axis=0)
    variances = concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral", detect_res_length=None,
                  label_length=None):
    """fluid.layers.detection_map (detection.py:1222) — VOC mAP over
    detection results. Runs as a host op (the reference kernel is
    CPU-only too); DetectRes/Label are flat [N,6]/[M,5|6] with optional
    per-image length tensors standing in for LoD."""
    helper = LayerHelper("detection_map", **locals())

    def state(dtype):
        return helper.create_variable_for_type_inference(
            dtype, stop_gradient=True)

    map_out = state("float32")
    inputs = {"Label": [label], "DetectRes": [detect_res]}
    if has_state is not None:
        inputs["HasState"] = [has_state]
    if input_states is not None:
        inputs["PosCount"] = [input_states[0]]
        inputs["TruePos"] = [input_states[1]]
        inputs["FalsePos"] = [input_states[2]]
        if len(input_states) >= 5:   # per-class row counts of TP/FP state
            inputs["TruePosLength"] = [input_states[3]]
            inputs["FalsePosLength"] = [input_states[4]]
    if detect_res_length is not None:
        inputs["DetectResLength"] = [detect_res_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    if out_states is not None:
        outputs = {"MAP": [map_out],
                   "AccumPosCount": [out_states[0]],
                   "AccumTruePos": [out_states[1]],
                   "AccumFalsePos": [out_states[2]]}
        if len(out_states) >= 5:
            outputs["AccumTruePosLength"] = [out_states[3]]
            outputs["AccumFalsePosLength"] = [out_states[4]]
    else:
        outputs = {"MAP": [map_out],
                   "AccumPosCount": [state("int32")],
                   "AccumTruePos": [state("float32")],
                   "AccumFalsePos": [state("float32")]}
    helper.append_op(type="detection_map", inputs=inputs, outputs=outputs,
                     attrs={"overlap_threshold": overlap_threshold,
                            "evaluate_difficult": evaluate_difficult,
                            "ap_type": ap_version,
                            "class_num": class_num,
                            "background_label": background_label})
    return map_out
