"""Detection layers — fluid/layers/detection.py surface subset over
ops/detection.py."""
from __future__ import annotations

from ..framework.layer_helper import LayerHelper

__all__ = ["yolo_box", "prior_box", "box_coder", "roi_align",
           "multiclass_nms", "anchor_generator", "density_prior_box",
           "roi_pool", "iou_similarity", "box_clip", "sigmoid_focal_loss"]


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes.name], "Scores": [scores.name]},
        attrs={"anchors": list(anchors), "class_num": class_num,
               "conf_thresh": conf_thresh,
               "downsample_ratio": downsample_ratio,
               "clip_bbox": clip_bbox})
    return boxes, scores


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes.name], "Variances": [var.name]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
               "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference("float32")
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder", inputs=inputs,
        outputs={"OutputBox": [out.name]},
        attrs={"code_type": code_type, "box_normalized": box_normalized})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_id=None,
              name=None):
    """RoIAlign (fluid.layers.roi_align parity).

    Note: with ``sampling_ratio<=0`` the reference adaptively picks
    ``ceil(roi_size/pooled_size)`` samples per bin per ROI; this build uses
    a fixed 2x2 grid instead (static shapes). Pass ``sampling_ratio>0`` for
    exact reference parity.
    """
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op(
        type="roi_align", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale,
               "sampling_ratio": sampling_ratio})
    return out


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """Host-side NMS (CPU-only in the reference too, multiclass_nms_op.cc):
    returns [M, 6] rows (label, score, x1, y1, x2, y2)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out.name]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    return out


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    """fluid.layers.anchor_generator (detection/anchor_generator_op.cc)."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors.name], "Variances": [variances.name]},
        attrs={"anchor_sizes": [float(v) for v in (anchor_sizes or [64., 128., 256., 512.])],
               "aspect_ratios": [float(v) for v in (aspect_ratios or [0.5, 1.0, 2.0])],
               "variances": [float(v) for v in (variance or [0.1, 0.1, 0.2, 0.2])],
               "stride": [float(v) for v in (stride or [16.0, 16.0])],
               "offset": float(offset)})
    return anchors, variances


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, flatten_to_2d=False, name=None):
    """fluid.layers.density_prior_box (detection/density_prior_box_op.cc)."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={"densities": [int(v) for v in (densities or [])],
               "fixed_sizes": [float(v) for v in (fixed_sizes or [])],
               "fixed_ratios": [float(v) for v in (fixed_ratios or [])],
               "variances": [float(v) for v in (variance or [0.1, 0.1, 0.2, 0.2])],
               "clip": bool(clip), "step_w": float(steps[0]),
               "step_h": float(steps[1]), "offset": float(offset),
               "flatten_to_2d": bool(flatten_to_2d)})
    return boxes, variances


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_batch_id=None, name=None):
    """fluid.layers.roi_pool (roi_pool_op.cc). Returns pooled features;
    argmax stays internal like the reference python wrapper."""
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int64")
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op(
        type="roi_pool", inputs=inputs,
        outputs={"Out": [out.name], "Argmax": [argmax.name]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale)})
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out.name]},
                     attrs={"box_normalized": bool(box_normalized)})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out.name]}, attrs={})
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    helper = LayerHelper("sigmoid_focal_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
                     outputs={"Out": [out.name]},
                     attrs={"gamma": float(gamma), "alpha": float(alpha)})
    return out
