"""Detection layers — fluid/layers/detection.py surface subset over
ops/detection.py."""
from __future__ import annotations

from ..framework.layer_helper import LayerHelper

__all__ = ["yolo_box", "prior_box", "box_coder", "roi_align",
           "multiclass_nms", "anchor_generator", "density_prior_box",
           "roi_pool", "iou_similarity", "box_clip", "sigmoid_focal_loss",
           "yolov3_loss", "bipartite_match", "target_assign",
           "rpn_target_assign", "generate_proposals",
           "distribute_fpn_proposals", "collect_fpn_proposals"]


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes.name], "Scores": [scores.name]},
        attrs={"anchors": list(anchors), "class_num": class_num,
               "conf_thresh": conf_thresh,
               "downsample_ratio": downsample_ratio,
               "clip_bbox": clip_bbox})
    return boxes, scores


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes.name], "Variances": [var.name]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
               "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference("float32")
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder", inputs=inputs,
        outputs={"OutputBox": [out.name]},
        attrs={"code_type": code_type, "box_normalized": box_normalized})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_id=None,
              name=None):
    """RoIAlign (fluid.layers.roi_align parity).

    Note: with ``sampling_ratio<=0`` the reference adaptively picks
    ``ceil(roi_size/pooled_size)`` samples per bin per ROI; this build uses
    the static bound min(8, ceil(feature/pooled)) instead (static
    shapes; >= reference density for large ROIs). Pass ``sampling_ratio>0`` for
    exact reference parity.
    """
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op(
        type="roi_align", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale,
               "sampling_ratio": sampling_ratio})
    return out


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """Host-side NMS (CPU-only in the reference too, multiclass_nms_op.cc):
    returns [M, 6] rows (label, score, x1, y1, x2, y2)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out.name]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    return out


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    """fluid.layers.anchor_generator (detection/anchor_generator_op.cc)."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors.name], "Variances": [variances.name]},
        attrs={"anchor_sizes": [float(v) for v in (anchor_sizes or [64., 128., 256., 512.])],
               "aspect_ratios": [float(v) for v in (aspect_ratios or [0.5, 1.0, 2.0])],
               "variances": [float(v) for v in (variance or [0.1, 0.1, 0.2, 0.2])],
               "stride": [float(v) for v in (stride or [16.0, 16.0])],
               "offset": float(offset)})
    return anchors, variances


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, flatten_to_2d=False, name=None):
    """fluid.layers.density_prior_box (detection/density_prior_box_op.cc)."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={"densities": [int(v) for v in (densities or [])],
               "fixed_sizes": [float(v) for v in (fixed_sizes or [])],
               "fixed_ratios": [float(v) for v in (fixed_ratios or [])],
               "variances": [float(v) for v in (variance or [0.1, 0.1, 0.2, 0.2])],
               "clip": bool(clip), "step_w": float(steps[0]),
               "step_h": float(steps[1]), "offset": float(offset),
               "flatten_to_2d": bool(flatten_to_2d)})
    return boxes, variances


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_batch_id=None, name=None):
    """fluid.layers.roi_pool (roi_pool_op.cc). Returns pooled features;
    argmax stays internal like the reference python wrapper."""
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int64")
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    helper.append_op(
        type="roi_pool", inputs=inputs,
        outputs={"Out": [out.name], "Argmax": [argmax.name]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale)})
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out.name]},
                     attrs={"box_normalized": bool(box_normalized)})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out.name]}, attrs={})
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    helper = LayerHelper("sigmoid_focal_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
                     outputs={"Out": [out.name]},
                     attrs={"gamma": float(gamma), "alpha": float(alpha)})
    return out


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """fluid.layers.yolov3_loss (detection.py:1001) over
    operators/detection/yolov3_loss_op.cc."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    obj_mask = helper.create_variable_for_type_inference(
        x.dtype, stop_gradient=True)
    match_mask = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    ins = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score]
    helper.append_op(
        type="yolov3_loss", inputs=ins,
        outputs={"Loss": [loss], "ObjectnessMask": [obj_mask],
                 "GTMatchMask": [match_mask]},
        attrs={"anchors": list(anchors), "anchor_mask": list(anchor_mask),
               "class_num": int(class_num),
               "ignore_thresh": float(ignore_thresh),
               "downsample_ratio": int(downsample_ratio),
               "use_label_smooth": bool(use_label_smooth),
               "scale_x_y": float(scale_x_y)})
    return loss


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    match_dist = helper.create_variable_for_type_inference(
        dist_matrix.dtype, stop_gradient=True)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type or "bipartite",
               "dist_threshold": float(dist_threshold
                                       if dist_threshold is not None else 0.5)})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    ins = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        ins["NegIndices"] = [negative_indices]
    helper.append_op(
        type="target_assign", inputs=ins,
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": int(mismatch_value or 0)})
    return out, out_weight


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """fluid.layers.rpn_target_assign (detection.py:308). Static-shape
    variant: index outputs are padded with -1 (the LoD replacement); the
    predicted score/loc gathers mask padded slots to zero so downstream
    losses see exact zeros there."""
    helper = LayerHelper("rpn_target_assign")
    loc_index = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    score_index = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    target_label = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    target_bbox = helper.create_variable_for_type_inference(
        bbox_pred.dtype, stop_gradient=True)
    bbox_inside_weight = helper.create_variable_for_type_inference(
        bbox_pred.dtype, stop_gradient=True)
    ins = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes], "ImInfo": [im_info]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    helper.append_op(
        type="rpn_target_assign",
        inputs=ins,
        outputs={"LocIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [bbox_inside_weight]},
        attrs={"rpn_batch_size_per_im": int(rpn_batch_size_per_im),
               "rpn_straddle_thresh": float(rpn_straddle_thresh),
               "rpn_fg_fraction": float(rpn_fg_fraction),
               "rpn_positive_overlap": float(rpn_positive_overlap),
               "rpn_negative_overlap": float(rpn_negative_overlap),
               "use_random": bool(use_random)})
    predicted_scores = _masked_batch_gather(helper, cls_logits, score_index)
    predicted_location = _masked_batch_gather(helper, bbox_pred, loc_index)
    return (predicted_scores, predicted_location, target_label, target_bbox,
            bbox_inside_weight)


def _masked_batch_gather(helper, x, index):
    """gather x[b, index[b]] with -1 indices producing zero rows (device-side
    glue for the static rpn_target_assign outputs)."""
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="masked_batch_gather",
                     inputs={"X": [x], "Index": [index]},
                     outputs={"Out": [out]}, attrs={})
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    helper = LayerHelper("generate_proposals", name=name)
    rpn_rois = helper.create_variable_for_type_inference(bbox_deltas.dtype)
    rpn_roi_probs = helper.create_variable_for_type_inference(scores.dtype)
    rois_num = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rpn_rois], "RpnRoiProbs": [rpn_roi_probs],
                 "RpnRoisNum": [rois_num]},
        attrs={"pre_nms_topN": int(pre_nms_top_n),
               "post_nms_topN": int(post_nms_top_n),
               "nms_thresh": float(nms_thresh), "min_size": float(min_size),
               "eta": float(eta)})
    if return_rois_num:
        return rpn_rois, rpn_roi_probs, rois_num
    return rpn_rois, rpn_roi_probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_level = max_level - min_level + 1
    multi_rois = [helper.create_variable_for_type_inference(fpn_rois.dtype)
                  for _ in range(n_level)]
    level_nums = [helper.create_variable_for_type_inference(
        "int32", stop_gradient=True) for _ in range(n_level)]
    restore_ind = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    ins = {"FpnRois": [fpn_rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op(
        type="distribute_fpn_proposals", inputs=ins,
        outputs={"MultiFpnRois": multi_rois,
                 "MultiLevelRoIsNum": level_nums,
                 "RestoreIndex": [restore_ind]},
        attrs={"min_level": int(min_level), "max_level": int(max_level),
               "refer_level": int(refer_level),
               "refer_scale": int(refer_scale)})
    if rois_num is not None:
        return multi_rois, restore_ind, level_nums
    return multi_rois, restore_ind


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    helper = LayerHelper("collect_fpn_proposals", name=name)
    fpn_rois = helper.create_variable_for_type_inference(multi_rois[0].dtype)
    rois_num = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    num_level = max_level - min_level + 1
    ins = {"MultiLevelRois": list(multi_rois[:num_level]),
           "MultiLevelScores": list(multi_scores[:num_level])}
    if rois_num_per_level is not None:
        ins["MultiLevelRoIsNum"] = list(rois_num_per_level[:num_level])
    helper.append_op(
        type="collect_fpn_proposals", inputs=ins,
        outputs={"FpnRois": [fpn_rois], "RoisNum": [rois_num]},
        attrs={"post_nms_topN": int(post_nms_top_n)})
    if rois_num_per_level is not None:
        return fpn_rois, rois_num
    return fpn_rois
