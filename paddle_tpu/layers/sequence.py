"""Sequence-op layer surface — fluid/layers/sequence_lod.py + the CRF/CTC
entries of fluid/layers/nn.py (linear_chain_crf:1696, crf_decoding:1797,
warpctc) over the dense padded ops in ops/sequence.py and ops/crf.py.

Dense convention: sequences are (batch, max_len, ...) plus an explicit
length tensor where the reference threads LoD.
"""
from __future__ import annotations

from ..framework.layer_helper import LayerHelper

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_reverse", "sequence_conv",
    "sequence_slice", "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_mask", "linear_chain_crf", "crf_decoding", "warpctc",
    "sequence_enumerate", "sequence_erase",
]


def sequence_pool(input, pool_type, length=None, pad_value=0.0):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_pool", inputs=ins,
                     outputs={"Out": [out.name]},
                     attrs={"pooltype": pool_type.upper(),
                            "pad_value": float(pad_value)})
    return out


def sequence_softmax(input, length=None):
    helper = LayerHelper("sequence_softmax")
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_softmax", inputs=ins,
                     outputs={"Out": [out.name]}, attrs={})
    return out


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_reverse", inputs=ins,
                     outputs={"Y": [out.name]}, attrs={})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, length=None,
                  bias_attr=None, param_attr=None, act=None, name=None):
    """fluid.layers.sequence_conv (sequence_conv_op.cc)."""
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    d = input.shape[-1]
    filt = helper.create_parameter(param_attr,
                                   shape=[filter_size * d, num_filters],
                                   dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Filter": [filt]}
    if length is not None:
        ins["Length"] = [length]
    if padding_start is None:
        padding_start = -((filter_size - 1) // 2)
    helper.append_op(
        type="sequence_conv", inputs=ins, outputs={"Out": [out.name]},
        attrs={"contextLength": int(filter_size),
               "contextStart": int(padding_start),
               "contextStride": int(filter_stride)})
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out.name], "OutLength": [out_len.name]}, attrs={})
    return out


def sequence_expand_as(x, y, y_length=None, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y]}
    if y_length is not None:
        ins["YLength"] = [y_length]
    helper.append_op(type="sequence_expand_as", inputs=ins,
                     outputs={"Out": [out.name]}, attrs={})
    return out


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Returns (Out, Length) like the reference sequence_pad."""
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    ins = {"X": [x], "PadValue": [pad_value]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_pad", inputs=ins,
                     outputs={"Out": [out.name], "Length": [out_len.name]},
                     attrs={"padded_length": -1 if maxlen is None
                            else int(maxlen)})
    return out, out_len


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        raise ValueError(
            "sequence_mask requires a static maxlen on TPU (the reference "
            "derives it from max(x) at run time, a dynamic shape XLA cannot "
            "compile); pass maxlen explicitly")
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out.name]},
                     attrs={"maxlen": -1 if maxlen is None else int(maxlen),
                            "out_dtype": dtype})
    return out


def linear_chain_crf(input, label, param_attr=None, length=None, name=None):
    """fluid.layers.linear_chain_crf (layers/nn.py:1696). input [B,T,D]
    emissions; label [B,T]; length [B]. Returns the NLL [B,1]; the
    transition parameter is created as '<name>.w' ([D+2, D])."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr, name=name)
    size = input.shape[-1]
    transition = helper.create_parameter(param_attr,
                                         shape=[size + 2, size],
                                         dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    e_exps = helper.create_variable_for_type_inference(input.dtype)
    t_exps = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Emission": [input], "Transition": [transition], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        type="linear_chain_crf", inputs=ins,
        outputs={"LogLikelihood": [ll.name], "Alpha": [alpha.name],
                 "EmissionExps": [e_exps.name],
                 "TransitionExps": [t_exps.name]},
        attrs={})
    return ll


def crf_decoding(input, param_attr, label=None, length=None, name=None):
    """fluid.layers.crf_decoding (layers/nn.py:1797): viterbi path, or the
    per-position correctness indicator when label is given."""
    helper = LayerHelper("crf_decoding", name=name)
    trans_name = (param_attr.name if hasattr(param_attr, "name")
                  else str(param_attr))
    transition = helper.main_program.global_block().var(trans_name)
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        ins["Label"] = [label]
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [path.name]}, attrs={})
    return path


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """fluid.layers.warpctc (warpctc_op.cc, padding mode): input [B,T,C]
    raw logits, label [B,Lmax]."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op(type="warpctc", inputs=ins,
                     outputs={"Loss": [loss.name]},
                     attrs={"blank": int(blank),
                            "norm_by_times": bool(norm_by_times)})
    return loss


def sequence_enumerate(input, win_size, pad_value=0, name=None, length=None):
    """fluid.layers.sequence_enumerate (sequence_lod.py:1234): sliding-window
    id enumeration; padded form returns (B, T, win_size)."""
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_enumerate", inputs=ins,
                     outputs={"Out": [out]},
                     attrs={"win_size": int(win_size),
                            "pad_value": int(pad_value)})
    return out


def sequence_erase(input, tokens, name=None, length=None):
    """fluid.layers.sequence_erase: drop listed tokens and left-compact;
    returns (Out, NewLength) in the padded convention."""
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    new_len = helper.create_variable_for_type_inference(
        "int64" if length is None else length.dtype, stop_gradient=True)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_erase", inputs=ins,
                     outputs={"Out": [out], "Length": [new_len]},
                     attrs={"tokens": list(tokens)})
    return out, new_len
