"""Weight-decay regularizers — parity with python/paddle/fluid/regularizer.py."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from .framework.layer_helper import LayerHelper

        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(
            type="scale", inputs={"X": [param]}, outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        out = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(
            type="sum", inputs={"X": [grad, decay]}, outputs={"Out": [out]}
        )
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from .framework.layer_helper import LayerHelper

        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(type="sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decay = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(type="scale", inputs={"X": [sign]}, outputs={"Out": [decay]},
                        attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(type="sum", inputs={"X": [grad, decay]}, outputs={"Out": [out]})
        return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    """Add weight-decay terms onto grads (per-param regularizer wins over the
    optimizer-level default) — parity with regularizer.py append_regularization_ops."""
    out = []
    for p, g in params_grads:
        if g is None:
            out.append((p, g))
            continue
        reg = getattr(p, "regularizer", None) or regularization
        if reg is None:
            out.append((p, g))
            continue
        new_g = reg(p, g, g.block)
        out.append((p, new_g))
    return out
