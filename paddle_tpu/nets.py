"""fluid.nets — composite network helpers (python/paddle/fluid/nets.py):
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention. Pure layer composition; everything fuses
under the whole-program jit.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """nets.py:29 — conv2d then pool2d."""
    conv_out = layers.conv2d(
        input, num_filters, filter_size, stride=conv_stride,
        padding=conv_padding, dilation=conv_dilation, groups=conv_groups,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """nets.py:141 — VGG-style conv stack (+BN/dropout per conv) + pool."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))
    n = len(conv_num_filter)

    def extend(obj):
        if not hasattr(obj, "__len__"):
            return [obj] * n
        assert len(obj) == n
        return list(obj)

    conv_padding = extend(conv_padding)
    conv_filter_size = extend(conv_filter_size)
    param_attr = extend(param_attr)
    conv_with_batchnorm = extend(conv_with_batchnorm)
    conv_batchnorm_drop_rate = extend(conv_batchnorm_drop_rate)

    for i in range(n):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            tmp, conv_num_filter[i], conv_filter_size[i],
            padding=conv_padding[i], param_attr=param_attr[i],
            act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(tmp, dropout_prob=drop_rate)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       length=None):
    """nets.py:256 — sequence_conv then sequence_pool (padded convention:
    optional ``length``)."""
    conv_out = layers.sequence_conv(input, num_filters, filter_size,
                                    param_attr=param_attr, act=act,
                                    bias_attr=bias_attr, length=length)
    return layers.sequence_pool(conv_out, pool_type, length=length)


def glu(input, dim=-1):
    """nets.py:328 — gated linear unit: a * sigmoid(b) over a split."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """nets.py:372 — multi-head scaled-dot attention over fluid layers
    (queries [B, Tq, D], keys/values [B, Tk, D])."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must share hidden size")
    d = queries.shape[-1]
    head_dim = d // num_heads

    def split_heads(x):
        if num_heads == 1:
            return x
        r = layers.reshape(x, shape=[0, 0, num_heads, head_dim])
        return layers.transpose(r, perm=[0, 2, 1, 3])

    def combine_heads(x):
        if num_heads == 1:
            return x
        t = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(t, shape=[0, 0, num_heads * head_dim])

    q = split_heads(queries)
    k = split_heads(keys)
    v = split_heads(values)
    scaled_q = layers.scale(q, scale=head_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    return combine_heads(ctx)
