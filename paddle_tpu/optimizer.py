"""Optimizer classes — parity with python/paddle/fluid/optimizer.py (4,304 LoC,
19 optimizers: SGD:842, Momentum:936, LarsMomentum:1486, Adagrad:1600, Adam:1716,
Adamax:1982, Dpsgd:2154, DecayedAdagrad:2249, Adadelta:2359, RMSProp:2478,
Ftrl:2666, Lamb:2825, ModelAverage:2997, EMA:3306, Pipeline:3556,
Recompute:3858, Lookahead:4150).

minimize() = append_backward (IR autodiff) + regularization + grad clip +
per-param optimizer update ops. The whole thing compiles into ONE XLA program
with the forward/backward — the reference's fuse_optimizer_ops_pass is
subsumed by XLA fusion.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .framework import unique_name
from .framework.backward import append_backward
from .framework.initializer import ConstantInitializer
from .framework.layer_helper import LayerHelper
from .framework.program import (
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
)

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "LarsMomentum", "LarsMomentumOptimizer", "Adagrad", "AdagradOptimizer",
    "DecayedAdagrad", "DecayedAdagradOptimizer", "Adadelta", "AdadeltaOptimizer",
    "Adam", "AdamOptimizer", "AdamW", "Adamax", "AdamaxOptimizer", "Dpsgd",
    "DpsgdOptimizer", "RMSProp", "RMSPropOptimizer", "Ftrl", "FtrlOptimizer",
    "Lamb", "LambOptimizer", "DGCMomentumOptimizer",
    "ExponentialMovingAverage", "ModelAverage",
    "RecomputeOptimizer", "LookaheadOptimizer", "PipelineOptimizer",
    "GradientMergeOptimizer",
]


class _EagerAcc:
    """Handle for an optimizer accumulator in dygraph mode (the eager
    counterpart of the persistable accumulator var)."""

    __slots__ = ("key", "name")

    def __init__(self, key, name):
        self.key = key
        self.name = name


class _EagerOptBlock:
    """Replays ``_append_optimize_op`` eagerly for dygraph training.

    The same ``_append_optimize_op`` methods that build the static optimize
    slice are executed here against jnp arrays: each ``append_op`` call runs
    the registered optimizer-op lowering (the single source of truth for the
    update math — reference dygraph mode likewise calls the same op kernels
    eagerly, imperative/tracer.cc) and writes ParamOut/accumulator outputs
    back in place.
    """

    def __init__(self, state):
        self.state = state          # accumulator key -> jnp array
        self._env = {}              # var name -> value for intra-step temps

    def resolve(self, v):
        import jax.numpy as jnp

        if isinstance(v, _EagerAcc):
            return self.state[v.key]
        if hasattr(v, "value") and hasattr(v, "_grad"):   # VarBase
            return v.value
        if isinstance(v, str):
            return self._env[v]
        if isinstance(v, (float, int)):
            return jnp.asarray(v, jnp.float32)
        return v                    # raw jnp/np array (the grad, lr)

    def append_op(self, type, inputs, outputs, attrs=None):
        from .framework.registry import LowerCtx, _FakeOp, get_op_spec
        from .tensor._dispatch import _next_eager_key

        ins = {slot: [self.resolve(v) for v in vs]
               for slot, vs in inputs.items() if vs}
        out_names = {slot: [getattr(v, "name", f"__tmp_{slot}_{i}")
                            for i, v in enumerate(vs)]
                     for slot, vs in outputs.items()}
        fake = _FakeOp(type, {s: [f"i{i}" for i in range(len(v))]
                              for s, v in ins.items()},
                       out_names, dict(attrs or {}), None)
        spec = get_op_spec(type)
        # stepped rng: rng-consuming optimizer ops (dpsgd's DP noise) must
        # draw FRESH randomness each eager step, like the executor stream
        outs = spec.lower(LowerCtx(None, None, {},
                                   rng_key=_next_eager_key()), fake, ins)
        for slot, vs in outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for v, val in zip(vs, vals):
                if val is None:
                    continue
                if isinstance(v, _EagerAcc):
                    self.state[v.key] = val
                elif hasattr(v, "value") and hasattr(v, "_grad"):
                    v.value = val
                else:
                    self._env[getattr(v, "name", str(v))] = val


class Optimizer:
    # fused flat-buffer sweep support: the fused op type this optimizer
    # lowers to when fusion is on (None = per-param path only)
    _fused_op_type: Optional[str] = None

    def __init__(self, learning_rate, regularization=None, grad_clip=None, name=None,
                 parameter_list=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var: Optional[Variable] = None
        self.type = "optimizer"
        # opt-in flat-buffer fused update sweep (see apply_gradients)
        self._fuse = False
        # dygraph mode: parameters to update + eager accumulator state
        self._parameter_list = parameter_list
        self._eager_block: Optional[_EagerOptBlock] = None
        self._eager_state: Dict[str, object] = {}

    # -- learning rate ------------------------------------------------------
    def _create_lr_var(self, program: Program) -> Variable:
        if isinstance(self._learning_rate, Variable):
            return self._learning_rate
        if self._lr_var is not None and self._lr_var.block.program is program:
            return self._lr_var
        from .layers.tensor import create_global_var

        name = unique_name.generate("learning_rate")
        self._lr_var = create_global_var(
            shape=[1], value=float(self._learning_rate), dtype="float32",
            persistable=True, name=name,
        )
        return self._lr_var

    @property
    def learning_rate_var(self):
        return self._lr_var

    def current_step_lr(self):
        return self._learning_rate

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name: str, param: Parameter, fill_value=0.0,
                         shape=None, dtype="float32") -> Variable:
        if self._eager_block is not None:
            import jax.numpy as jnp

            key = (param.name, name)
            if key not in self._eager_state:
                self._eager_state[key] = jnp.full(
                    tuple(shape if shape is not None else param.shape),
                    float(fill_value), dtype=jnp.float32)
            return _EagerAcc(key, name)
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        acc_name = unique_name.generate(f"{param.name}_{name}")
        shape = list(shape if shape is not None else param.shape)
        main_block = default_main_program().global_block()
        var = main_block.create_var(
            name=acc_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        startup_block = default_startup_program().global_block()
        sv = startup_block.create_var(
            name=acc_name, shape=shape, dtype=dtype, persistable=True
        )
        ConstantInitializer(fill_value)(sv, startup_block)
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    # -- main entry ---------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .dygraph import base as _dyg

        if _dyg.enabled():
            return self._dygraph_minimize(
                loss, parameter_list or self._parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_optimize(loss, startup_program, params_grads)
        return optimize_ops, params_grads

    # -- dygraph (eager) path ----------------------------------------------
    def _dygraph_minimize(self, loss, parameter_list):
        """Reference dygraph contract: loss.backward() fills VarBase grads,
        minimize() applies the update (imperative optimizer path,
        fluid/optimizer.py minimize under in_dygraph_mode)."""
        import jax.numpy as jnp

        if parameter_list is None:
            raise ValueError(
                "dygraph minimize() needs parameters: pass parameter_list "
                "to the optimizer constructor or to minimize()")
        params = [p for p in parameter_list
                  if getattr(p, "trainable", True)
                  and not getattr(p, "stop_gradient", False)]
        if loss is not None and all(p._grad is None for p in params):
            loss.backward()
        pgs = [(p, p._grad) for p in params if p._grad is not None]
        if self._grad_clip is not None:
            pgs = self._eager_clip(pgs)
        pgs = self._eager_regularize(pgs)
        lr = jnp.asarray(self._eager_lr(), jnp.float32)
        blk = _EagerOptBlock(self._eager_state)
        self._eager_block = blk
        try:
            for p, g in pgs:
                self._append_optimize_op(blk, (p, g), lr)
            self._finish_update(blk, pgs)
        finally:
            self._eager_block = None
        return [], pgs

    def _eager_lr(self):
        lr = self._learning_rate
        if callable(lr) and not isinstance(lr, (int, float)):
            val = float(lr())
            if hasattr(lr, "step"):
                lr.step()
            return val
        return float(lr)

    def _eager_clip(self, pgs):
        import jax.numpy as jnp

        from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                           GradientClipByValue)

        c = self._grad_clip
        if isinstance(c, GradientClipByValue):
            return [(p, jnp.clip(g, c.min, c.max)) for p, g in pgs]
        if isinstance(c, GradientClipByNorm):
            out = []
            for p, g in pgs:
                n = jnp.sqrt(jnp.sum(jnp.square(g)))
                out.append((p, g * jnp.minimum(1.0, c.clip_norm /
                                               jnp.maximum(n, 1e-12))))
            return out
        if isinstance(c, GradientClipByGlobalNorm):
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for _, g in pgs))
            scale = c.clip_norm / jnp.maximum(gn, c.clip_norm)
            return [(p, g * scale) for p, g in pgs]
        return pgs

    def _eager_regularize(self, pgs):
        from .regularizer import L1DecayRegularizer, L2DecayRegularizer

        out = []
        for p, g in pgs:
            reg = (p.optimize_attr or {}).get("regularizer") \
                if hasattr(p, "optimize_attr") and p.optimize_attr else None
            reg = reg or self.regularization
            if isinstance(reg, L2DecayRegularizer):
                g = g + reg._coeff * p.value
            elif isinstance(reg, L1DecayRegularizer):
                import jax.numpy as jnp
                g = g + reg._coeff * jnp.sign(p.value)
            out.append((p, g))
        return out

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def apply_gradients(self, params_grads):
        from .framework.core import get_flag

        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        program = default_main_program()
        # every op appended here is the optimize slice
        # (clone(for_test=True) strips it by this role tag)
        with program.op_role_guard(program.OP_ROLE_OPTIMIZE):
            # grad clip first (reference fluid/clip.py appends clip ops),
            # then regularization (weight decay appended onto grads).
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            params_grads = self._append_regularization_ops(params_grads)
            lr = self._create_lr_var(program)
            if (self._fused_op_type is not None
                    and (self._fuse or get_flag("FLAGS_fuse_optimizer"))):
                return self._apply_fused_gradients(program, params_grads, lr)
            ops = []
            for p, g in params_grads:
                if g is None:
                    continue
                ops.append(self._append_optimize_op(
                    program.global_block(), (p, g), lr))
            self._finish_update(program.global_block(), params_grads)
        return ops

    # -- fused flat-buffer sweep -------------------------------------------
    def _fused_hparam_key(self, param: Parameter) -> Tuple:
        """Params sharing a key share one fused update op (and one flat
        accumulator layout): same storage dtype + same per-param
        hyperparameters (ParamAttr.learning_rate multiplier; AdamW adds its
        decay-exclusion bit). Regularization and clipping are already folded
        into the grads at this point, so they never split groups."""
        mult = (getattr(param, "optimize_attr", None) or {}) \
            .get("learning_rate", 1.0)
        return (str(param.dtype), float(mult))

    def _apply_fused_gradients(self, program, params_grads, lr_var):
        """One fused update op per (dtype, hparam-signature) group instead of
        one op per parameter: the lowering concatenates the group into a
        flat megabuffer, runs a single vectorized update, and slices the
        new params back out. Optimizer moments live in the SAME flat layout
        as persistable [numel] buffers — the executor donates each group's
        moments as one buffer instead of hundreds of tiny donations, and
        checkpoints save/restore them under one name per group
        (docs/memory_levers.md)."""
        block = program.global_block()
        groups: Dict[Tuple, List[Tuple[Parameter, Variable]]] = {}
        for p, g in params_grads:
            if g is None:
                continue
            groups.setdefault(self._fused_hparam_key(p), []).append((p, g))
        ops = []
        for key in sorted(groups, key=repr):
            ops.append(self._append_fused_optimize_op(
                block, groups[key], lr_var, key))
        self._finish_update(block, params_grads)
        # self-report the fusion win: N params collapsed into G update ops
        from .observability import metrics as _obs_metrics

        _reg = _obs_metrics.default_registry()
        _reg.gauge(
            "paddle_fused_optimizer_groups",
            "Fused update ops in the last fused apply_gradients",
            ("optimizer",)).labels(self.type).set(len(groups))
        _reg.gauge(
            "paddle_fused_optimizer_params",
            "Parameters covered by the last fused apply_gradients",
            ("optimizer",)).labels(self.type).set(
                sum(len(v) for v in groups.values()))
        return ops

    def _add_group_accumulator(self, name: str, key, numel: int,
                               fill_value=0.0, shape=None,
                               dtype="float32") -> Variable:
        """Flat accumulator for one fused group (the group analogue of
        _add_accumulator; names are deterministic given build order, so a
        rebuilt identical program resumes from the same checkpoint)."""
        tag = f"{name}@{key!r}"
        if name in self._accumulators and tag in self._accumulators[name]:
            return self._accumulators[name][tag]
        acc_name = unique_name.generate(f"fused_{self.type}_{name}")
        shape = list(shape if shape is not None else [numel])
        main_block = default_main_program().global_block()
        var = main_block.create_var(
            name=acc_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        startup_block = default_startup_program().global_block()
        sv = startup_block.create_var(
            name=acc_name, shape=shape, dtype=dtype, persistable=True
        )
        ConstantInitializer(fill_value)(sv, startup_block)
        self._accumulators.setdefault(name, {})[tag] = var
        return var

    def _append_fused_optimize_op(self, block, pgs, lr_var, key):
        raise NotImplementedError

    def _append_regularization_ops(self, params_grads):
        from .regularizer import append_regularization_ops

        return append_regularization_ops(params_grads, self.regularization)

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    def _param_lr(self, param: Parameter, lr_var):
        """Per-param learning-rate multiplier (ParamAttr.learning_rate)."""
        opt_attr = getattr(param, "optimize_attr", None)
        mult = (opt_attr or {}).get("learning_rate", 1.0)
        if mult == 1.0:
            return lr_var
        if self._eager_block is not None:
            return lr_var * float(mult)
        from .layers.tensor import scale as scale_layer

        return scale_layer(lr_var, scale=float(mult))


class SGDOptimizer(Optimizer):
    """fluid.optimizer.SGD (optimizer.py:842)."""

    _fused_op_type = "fused_sgd"

    def __init__(self, learning_rate, regularization=None, grad_clip=None, name=None, parameter_list=None,
                 fuse=False):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "sgd"
        self._fuse = bool(fuse)

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p]},
        )

    def _append_fused_optimize_op(self, block, pgs, lr_var, key):
        params = [p for p, _ in pgs]
        return block.append_op(
            type="fused_sgd",
            inputs={"Param": params, "Grad": [g for _, g in pgs],
                    "LearningRate": [lr_var]},
            outputs={"ParamOut": params},
            attrs={"lr_mult": key[1]},
        )


class MomentumOptimizer(Optimizer):
    """fluid.optimizer.Momentum (optimizer.py:936)."""

    _fused_op_type = "fused_momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, grad_clip=None, name=None, parameter_list=None,
                 fuse=False):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._fuse = bool(fuse)

    def _append_fused_optimize_op(self, block, pgs, lr_var, key):
        params = [p for p, _ in pgs]
        numel = sum(int(np.prod(p.shape)) for p in params)
        velocity = self._add_group_accumulator("velocity", key, numel)
        return block.append_op(
            type="fused_momentum",
            inputs={"Param": params, "Grad": [g for _, g in pgs],
                    "Velocity": [velocity], "LearningRate": [lr_var]},
            outputs={"ParamOut": params, "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov,
                   "lr_mult": key[1]},
        )

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        velocity = self._add_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [velocity],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class DGCMomentumOptimizer(Optimizer):
    """fluid.optimizer.DGCMomentumOptimizer (optimizer.py:1071): momentum
    with Deep Gradient Compression — top-k sparsified gradient exchange
    with local residual accumulation and momentum masking. The reference
    pairs this with SparseAllReduceOpHandle (top-k gather over NCCL rings);
    the dgc_momentum lowering reduces the masked gradient over the data-
    parallel mesh axis instead (ops/optimizer_ops.py)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 num_trainers=None, regularization=None, grad_clip=None,
                 name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "dgc_momentum"
        self._momentum = momentum
        self._rampup_begin_step = float(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        self._sparsity = list(sparsity)
        self._use_nesterov = use_nesterov

    def _cur_sparsity(self):
        # the reference interpolates the sparsity schedule on-device from
        # the global step; a static schedule list with the final value as
        # steady state covers the same rampup capability
        return float(self._sparsity[-1])

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        u = self._add_accumulator("dgc_u", p)
        v = self._add_accumulator("dgc_v", p)
        step = _get_or_create_global_step()
        return block.append_op(
            type="dgc_momentum",
            inputs={"Param": [p], "Grad": [g], "U": [u], "V": [v],
                    "CurrentStep": [step],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "UOut": [u], "VOut": [v]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov,
                   "sparsity": self._cur_sparsity(),
                   "rampup_begin_step": self._rampup_begin_step,
                   "ring_id": 0},
        )


class LarsMomentumOptimizer(Optimizer):
    """fluid.optimizer.LarsMomentum (optimizer.py:1486)."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, grad_clip=None,
                 name=None, epsilon=0.0, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        velocity = self._add_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [velocity],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   "epsilon": self._epsilon},
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 grad_clip=None, name=None, initial_accumulator_value=0.0,
                 parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "adagrad"
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        moment = self._add_accumulator("moment", p, fill_value=self._initial)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [moment],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, grad_clip=None, name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        moment = self._add_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [moment],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, grad_clip=None, name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        g_acc = self._add_accumulator("_avg_squared_grad", p)
        u_acc = self._add_accumulator("_avg_squared_update", p)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [g_acc],
                    "AvgSquaredUpdate": [u_acc]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [g_acc],
                     "AvgSquaredUpdateOut": [u_acc]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class AdamOptimizer(Optimizer):
    """fluid.optimizer.Adam (optimizer.py:1716)."""

    _fused_op_type = "fused_adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 regularization=None, grad_clip=None, name=None, lazy_mode=False,
                 parameter_list=None, fuse=False):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._fuse = bool(fuse)

    def _append_fused_optimize_op(self, block, pgs, lr_var, key):
        params = [p for p, _ in pgs]
        numel = sum(int(np.prod(p.shape)) for p in params)
        m1 = self._add_group_accumulator("moment1", key, numel)
        m2 = self._add_group_accumulator("moment2", key, numel)
        b1p = self._add_group_accumulator("beta1_pow", key, 1, fill_value=1.0)
        b2p = self._add_group_accumulator("beta2_pow", key, 1, fill_value=1.0)
        attrs = dict(self._op_attrs())
        attrs["lr_mult"] = key[1]
        if len(key) > 2 and not key[2]:    # AdamW group excluded from decay
            attrs.pop("coeff", None)
        return block.append_op(
            type="fused_adamw" if "coeff" in attrs else "fused_adam",
            inputs={"Param": params, "Grad": [g for _, g in pgs],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [lr_var]},
            outputs={"ParamOut": params, "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs=attrs,
        )

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=1.0, shape=[1])
        b2p = self._add_accumulator("beta2_pow_acc", p, fill_value=1.0, shape=[1])
        return block.append_op(
            type=self.type,
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs=self._op_attrs(),
        )

    def _op_attrs(self):
        return {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}


class AdamW(AdamOptimizer):
    """Decoupled weight decay Adam (paddle.optimizer.AdamW surface)."""

    _fused_op_type = "fused_adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 weight_decay=0.01, regularization=None, grad_clip=None, name=None,
                 apply_decay_param_fun=None, parameter_list=None, fuse=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization,
                         grad_clip, name, parameter_list=parameter_list,
                         fuse=fuse)
        self.type = "adamw"
        self._coeff = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _fused_hparam_key(self, param):
        with_decay = (self._apply_decay_param_fun is None
                      or bool(self._apply_decay_param_fun(param.name)))
        return super()._fused_hparam_key(param) + (with_decay,)

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            saved, self.type = self.type, "adam"
            try:
                return super()._append_optimize_op(block, param_and_grad, lr_var)
            finally:
                self.type = saved
        return super()._append_optimize_op(block, param_and_grad, lr_var)

    def _op_attrs(self):
        attrs = super()._op_attrs()
        if self.type == "adamw":
            attrs["coeff"] = self._coeff
        return attrs


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 regularization=None, grad_clip=None, name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        moment = self._add_accumulator("moment", p)
        inf_norm = self._add_accumulator("inf_norm", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                    shape=[1])
        return block.append_op(
            type="adamax",
            inputs={"Param": [p], "Grad": [g], "Moment": [moment],
                    "InfNorm": [inf_norm], "Beta1Pow": [b1p],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        for p, g in params_grads:
            if g is None:
                continue
            if self._eager_block is not None:
                b1p = _EagerAcc((p.name, "beta1_pow_acc"), "beta1_pow_acc")
            else:
                b1p = self._accumulators["beta1_pow_acc"][p.name]
            block.append_op(
                type="scale",
                inputs={"X": [b1p]},
                outputs={"Out": [b1p]},
                attrs={"scale": self._beta1},
            )


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1e-8, regularization=None, grad_clip=None, name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "dpsgd"
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        return block.append_op(
            type="dpsgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p]},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, grad_clip=None, name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        ms = self._add_accumulator("mean_square", p)
        mom = self._add_accumulator("momentum", p)
        inputs = {"Param": [p], "Grad": [g], "MeanSquare": [ms], "Moment": [mom],
                  "LearningRate": [self._param_lr(p, lr_var)]}
        outputs = {"ParamOut": [p], "MeanSquareOut": [ms], "MomentOut": [mom]}
        if self._centered:
            mg = self._add_accumulator("mean_grad", p)
            inputs["MeanGrad"] = [mg]
            outputs["MeanGradOut"] = [mg]
        return block.append_op(
            type="rmsprop", inputs=inputs, outputs=outputs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, grad_clip=None, name=None, parameter_list=None):
        super().__init__(learning_rate, regularization, grad_clip, name,
                         parameter_list=parameter_list)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        sq = self._add_accumulator("squared", p)
        lin = self._add_accumulator("linear", p)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._param_lr(p, lr_var)]},
            outputs={"ParamOut": [p], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(AdamOptimizer):
    """fluid.optimizer.Lamb (optimizer.py:2825)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, regularization=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None, parameter_list=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization,
                         grad_clip, name, parameter_list=parameter_list)
        self.type = "lamb"
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _op_attrs(self):
        attrs = super()._op_attrs()
        attrs["weight_decay"] = self._weight_decay
        return attrs

    def _append_optimize_op(self, block, param_and_grad, lr_var):
        p, g = param_and_grad
        if self._exclude_fn is not None and self._exclude_fn(p):
            saved = self._weight_decay
            self._weight_decay = 0.0
            try:
                return super()._append_optimize_op(block, param_and_grad, lr_var)
            finally:
                self._weight_decay = saved
        return super()._append_optimize_op(block, param_and_grad, lr_var)


class ExponentialMovingAverage:
    """fluid.optimizer.ExponentialMovingAverage (optimizer.py:3306).

    Maintains EMA shadow vars updated after each optimizer step; apply()/
    restore() swap params for evaluation.
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._ema_vars: Dict[str, Variable] = {}
        self._params: List[Parameter] = []

    def update(self):
        block = default_main_program().global_block()
        startup = default_startup_program().global_block()
        for p in block.all_parameters():
            if not p.trainable:
                continue
            ema_name = self._name + p.name + ".ema"
            ema = block.create_var(name=ema_name, shape=p.shape, dtype=p.dtype,
                                   persistable=True, stop_gradient=True)
            sv = startup.create_var(name=ema_name, shape=p.shape, dtype=p.dtype,
                                    persistable=True)
            ConstantInitializer(0.0)(sv, startup)
            self._ema_vars[p.name] = ema
            self._params.append(p)
            # ema = decay*ema + (1-decay)*param
            block.append_op(
                type="ema_update",
                inputs={"Param": [p], "Ema": [ema]},
                outputs={"EmaOut": [ema]},
                attrs={"decay": self._decay},
            )

    def apply(self, executor, need_restore=True):
        import numpy as _np

        from .framework.executor import global_scope

        scope = global_scope()
        self._backup = {}
        for p in self._params:
            self._backup[p.name] = scope.find_var(p.name)
            ema = scope.find_var(self._ema_vars[p.name].name)
            if ema is not None:
                scope.set_var(p.name, ema)
        return _EMAGuard(self, executor, need_restore)

    def restore(self, executor=None):
        from .framework.executor import global_scope

        scope = global_scope()
        for name, val in getattr(self, "_backup", {}).items():
            scope.set_var(name, val)


class _EMAGuard:
    def __init__(self, ema, executor, need_restore):
        self._ema, self._executor, self._need_restore = ema, executor, need_restore

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._need_restore:
            self._ema.restore(self._executor)


class ModelAverage(Optimizer):
    """fluid.optimizer.ModelAverage (optimizer.py:2997) — simplified EMA-style
    parameter averaging over a sliding window."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, None, name)
        self._ema = ExponentialMovingAverage(decay=1.0 - average_window_rate)

    def update(self):
        self._ema.update()

    def apply(self, executor, need_restore=True):
        return self._ema.apply(executor, need_restore)

    def restore(self, executor=None):
        self._ema.restore(executor)


class RecomputeOptimizer(Optimizer):
    """fluid.optimizer.Recompute (optimizer.py:3858): wraps an inner optimizer;
    checkpoints mark recompute segments.  append_backward re-emits each
    segment's forward ops into the backward region behind a recompute_barrier
    (lax.optimization_barrier) so XLA cannot CSE them away — activations
    between checkpoints are rematerialized instead of stored (see
    framework/backward.py _RecomputePlan)."""

    def __init__(self, optimizer: Optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks,
                               checkpoints=self._checkpoints)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        return self.apply_optimize(loss, startup_program, params_grads), params_grads


class PipelineOptimizer:
    """fluid.optimizer.PipelineOptimizer (reference optimizer.py:3556-3858).

    The reference splits block-0 into section sub-programs run by
    SectionWorker threads over scope queues; here minimize records a stage
    split on the Program and the Executor compiles the forward as GPipe
    stages over a ("pp", num_stages) mesh axis with a lax.scan microbatch
    schedule — see parallel/pipeline_program.py. cut_list (lists of cut
    Variables) picks the stage boundaries like the reference; with no
    cut_list the forward is split evenly into num_stages. place_list /
    concurrency_list / queue_size / start_cpu_core_id are accepted for API
    parity and ignored (XLA owns placement and scheduling on TPU).
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0, num_microbatches=None, num_stages=None,
                 remat_policy=None):
        from .parallel import remat as _remat

        self._optimizer = optimizer
        self._cut_list = cut_list
        if num_stages is None:
            num_stages = (len(cut_list) + 1) if cut_list else 2
        self._num_stages = int(num_stages)
        self._num_microbatches = int(num_microbatches
                                     or max(1, self._num_stages))
        # named remat policy (parallel/remat.py) applied to each STAGE body:
        # stage activations are recomputed in the schedule's backward
        # instead of saved across all M+S-1 scan ticks
        self._remat_policy = _remat.resolve(remat_policy).name \
            if remat_policy is not None else "none"

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .parallel.pipeline_program import annotate_pipeline

        block = loss.block
        program = block.program
        n_fwd = len(block.ops)
        params_grads = self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set)
        bwd_end = len(block.ops)
        opt_ops = self._optimizer.apply_optimize(
            loss, startup_program, params_grads)
        annotate_pipeline(
            program, loss, n_fwd=n_fwd, bwd_end=bwd_end,
            num_stages=self._num_stages,
            num_microbatches=self._num_microbatches,
            cut_list=self._cut_list,
            trainable_params=[p.name for p, g in params_grads
                              if g is not None],
            remat_policy=self._remat_policy)
        return opt_ops, params_grads


class GradientMergeOptimizer:
    """Batch-merge / gradient accumulation — the reference's
    multi_batch_merge_pass (framework/ir/multi_batch_merge_pass.cc) as an
    optimizer wrapper: the Executor runs the forward+backward region as a
    lax.scan over k microbatch slices of the fed batch and applies the
    inner optimizer once on the averaged gradients
    (parallel/grad_merge.py). With a mean loss this is numerically the
    same step as feeding the full batch at once — but peak activation
    memory drops by ~k."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True,
                 remat_policy=None, acc_dtype="float32"):
        from .parallel import remat as _remat

        self._optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = bool(avg)
        # microbatch gradient-accumulator dtype. f32 default regardless of
        # the param/grad dtype: bf16 accumulation drifts over k steps (8-bit
        # mantissa swallows small addends once the sum grows) — tested in
        # tests/test_comm_opt.py. Override only to trade accuracy for the
        # accumulator's HBM (e.g. "bfloat16" halves it).
        if acc_dtype not in ("float32", "bfloat16", "float16"):
            raise ValueError(
                f"acc_dtype {acc_dtype!r}: expected float32/bfloat16/float16")
        self.acc_dtype = acc_dtype
        # named remat policy (parallel/remat.py) recorded on the annotation
        # so one knob drives all three parallel paths; a grad-merge program
        # carries explicit gradient ops, so non-"none" policies only change
        # behavior when the scanned fwd/bwd region is differentiated again
        self._remat_policy = _remat.resolve(remat_policy).name \
            if remat_policy is not None else "none"

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .parallel.grad_merge import annotate_grad_merge

        block = loss.block
        program = block.program
        params_grads = self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set)
        bwd_end = len(block.ops)
        opt_ops = self._optimizer.apply_optimize(
            loss, startup_program, params_grads)
        annotate_grad_merge(
            program, loss, bwd_end, self.k_steps,
            [g.name for p, g in params_grads if g is not None],
            avg=self.avg, remat_policy=self._remat_policy,
            acc_dtype=str(self.acc_dtype))
        return opt_ops, params_grads


class LookaheadOptimizer:
    """fluid.optimizer.LookaheadOptimizer (optimizer.py:4150): fast/slow weights."""

    def __init__(self, inner_optimizer: Optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        ops, params_grads = self.inner_optimizer.minimize(loss, startup_program)
        block = default_main_program().global_block()
        startup = default_startup_program().global_block()
        # slow param copies + periodic interpolation via lookahead_update op
        step = _get_or_create_global_step()
        for p, g in params_grads:
            slow_name = p.name + "@SLOW"
            slow = block.create_var(name=slow_name, shape=p.shape, dtype=p.dtype,
                                    persistable=True, stop_gradient=True)
            sv = startup.create_var(name=slow_name, shape=p.shape, dtype=p.dtype,
                                    persistable=True)
            # initialize slow weights to the initial fast weights
            startup.append_op(type="assign", inputs={"X": [p.name]},
                              outputs={"Out": [slow_name]})
            block.append_op(
                type="lookahead_update",
                inputs={"Param": [p], "Slow": [slow], "Step": [step]},
                outputs={"ParamOut": [p], "SlowOut": [slow]},
                attrs={"alpha": self.alpha, "k": self.k},
            )
        return ops, params_grads


def _get_or_create_global_step() -> Variable:
    """Persistable int64 step counter incremented once per run."""
    main = default_main_program()
    block = main.global_block()
    name = "@LR_DECAY_COUNTER@"
    if block.has_var(name):
        return block.var(name)
    var = block.create_var(name=name, shape=[1], dtype="int64", persistable=True,
                           stop_gradient=True)
    startup = default_startup_program().global_block()
    sv = startup.create_var(name=name, shape=[1], dtype="int64", persistable=True)
    ConstantInitializer(0.0)(sv, startup)
    block._prepend_op(
        type="increment", inputs={"X": [var]}, outputs={"Out": [var]},
        attrs={"step": 1.0},
    )
    return var


# Short aliases matching paddle 2.0-preview naming
SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Dpsgd = DpsgdOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
