"""Program debugging utilities — parity with
python/paddle/fluid/debugger.py (pprint_program_codes, draw_block_graphviz)
and net_drawer.py.

Emits DOT text directly (no graphviz binary needed to produce the .dot;
render with any graphviz viewer).

Renderings annotate each variable with the shape/dtype the static
analysis pass propagates (paddle_tpu.analysis.propagate_block — the same
registry ``infer_shape`` / ``jax.eval_shape`` machinery the shape checker
runs), marking ``!`` where propagation contradicts the declared
metadata. Pass ``annotate=False`` for the raw declared view."""
from __future__ import annotations

from .framework.program import Program

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _propagated(block, annotate: bool):
    """{var name: (shape, dtype)} from the analysis pass; {} when
    annotation is off or propagation is unavailable (never raises — a
    debugger must render broken programs, that is its job)."""
    if not annotate:
        return {}
    try:
        from .analysis import propagate_block

        return propagate_block(block)
    except Exception:
        return {}


def _sharding_info(block, annotate: bool):
    """(specs, {op_idx: [reshard notes]}) from the GSPMD propagation pass
    (paddle_tpu/sharding/) — ({}, {}) when the program carries no
    annotations or propagation is unavailable (never raises)."""
    if not annotate:
        return {}, {}
    try:
        from .sharding import propagate_program
        from .sharding.spec import annotated_vars, mesh_axes_of

        program = block.program
        if not annotated_vars(program) and mesh_axes_of(program) is None:
            return {}, {}
        res = propagate_program(program)
        reshards = {}
        for r in res.reshards:
            if r.block_idx == block.idx:
                reshards.setdefault(r.op_idx, []).append(
                    f"{r.kind} {r.var!r} ~{r.bytes_est}B")
        return res.specs, reshards
    except Exception:
        return {}, {}


def _var_line(v, prop, shard_specs=()):
    tag = "param" if getattr(v, "persistable", False) else "var"
    decl_shape = getattr(v, "shape", None)
    decl_dtype = getattr(v, "dtype", None)
    line = f"  {tag} {v.name}: shape={decl_shape} dtype={decl_dtype}"
    hit = prop.get(v.name)
    if hit is not None:
        p_shape, p_dtype = hit
        if tuple(p_shape) != tuple(decl_shape or ()) or p_dtype != decl_dtype:
            line += f"  [propagated shape={tuple(p_shape)} dtype={p_dtype} !]"
        else:
            line += "  [propagated ok]"
    spec = shard_specs.get(v.name) if shard_specs else None
    if spec is not None:
        from .sharding.spec import is_replicated, spec_str

        if not is_replicated(spec):
            line += f"  [spec {spec_str(spec)}]"
        elif getattr(v, "sharding", None) is not None:
            line += "  [spec replicated]"
    return line


def pprint_block_codes(block, show_backward=False, annotate=True):
    prop = _propagated(block, annotate)
    shard_specs, reshards = _sharding_info(block, annotate)
    lines = [f"block {block.idx} (parent {block.parent_idx}):"]
    for v in block.vars.values():
        lines.append(_var_line(v, prop, shard_specs))
    for i, op in enumerate(block.ops):
        if not show_backward and op.type.endswith("_grad"):
            continue
        ins = ", ".join(f"{k}={v}" for k, v in (op.inputs or {}).items() if v)
        outs = ", ".join(f"{k}={v}" for k, v in (op.outputs or {}).items()
                         if v)
        # ops with no outputs (send, barrier, prints) render with an
        # explicit empty arrow instead of crashing the formatter
        line = f"  {op.type}({ins}) -> {outs if outs else '()'}"
        if i in reshards:
            # implied layout change on this edge — the "why did this
            # reshard" breadcrumb (docs/sharding.md runbook)
            line += "  [RESHARD " + "; ".join(reshards[i]) + "]"
        lines.append(line)
    return "\n".join(lines)


def pprint_program_codes(program: Program, show_backward=False,
                         annotate=True) -> str:
    text = "\n".join(pprint_block_codes(b, show_backward, annotate=annotate)
                     for b in program.blocks)
    print(text)
    return text


def draw_block_graphviz(block, highlights=None, path="./temp.dot",
                        annotate=True) -> str:
    """Write the block's op/var dataflow as a DOT digraph (reference
    debugger.py draw_block_graphviz). Var nodes carry the propagated
    shape/dtype annotation when available."""
    highlights = set(highlights or ())
    prop = _propagated(block, annotate)
    shard_specs, _reshards = _sharding_info(block, annotate)
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}
    for i, v in enumerate(block.vars.values()):
        var_ids[v.name] = f"var_{i}"
        color = ', style=filled, fillcolor="yellow"' \
            if v.name in highlights else ""
        shape = "box" if getattr(v, "persistable", False) else "ellipse"
        hit = prop.get(v.name)
        label = v.name
        if hit is not None:
            p_shape, p_dtype = hit
            label += f"\\n{list(p_shape)} {p_dtype}"
        spec = shard_specs.get(v.name)
        if spec is not None and any(e is not None for e in spec):
            from .sharding.spec import spec_str

            label += f"\\n{spec_str(spec)}"
        lines.append(f'  var_{i} [label="{label}", shape={shape}{color}];')
    for j, op in enumerate(block.ops):
        lines.append(f'  op_{j} [label="{op.type}", shape=record, '
                     f'style=filled, fillcolor="lightgrey"];')
        for names in (op.inputs or {}).values():
            for n in names:
                if n in var_ids:
                    lines.append(f"  {var_ids[n]} -> op_{j};")
        for names in (op.outputs or {}).values():
            for n in names:
                if n in var_ids:
                    lines.append(f"  op_{j} -> {var_ids[n]};")
    lines.append("}")
    text = "\n".join(lines)
    with open(path, "w") as f:
        f.write(text)
    return text
