"""Program debugging utilities — parity with
python/paddle/fluid/debugger.py (pprint_program_codes, draw_block_graphviz)
and net_drawer.py.

Emits DOT text directly (no graphviz binary needed to produce the .dot;
render with any graphviz viewer)."""
from __future__ import annotations

from .framework.program import Program

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def pprint_block_codes(block, show_backward=False):
    lines = [f"block {block.idx} (parent {block.parent_idx}):"]
    for v in block.vars.values():
        tag = "param" if getattr(v, "persistable", False) else "var"
        lines.append(f"  {tag} {v.name}: shape={getattr(v, 'shape', None)} "
                     f"dtype={getattr(v, 'dtype', None)}")
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        ins = ", ".join(f"{k}={v}" for k, v in op.inputs.items() if v)
        outs = ", ".join(f"{k}={v}" for k, v in op.outputs.items() if v)
        lines.append(f"  {op.type}({ins}) -> {outs}")
    return "\n".join(lines)


def pprint_program_codes(program: Program, show_backward=False) -> str:
    text = "\n".join(pprint_block_codes(b, show_backward)
                     for b in program.blocks)
    print(text)
    return text


def draw_block_graphviz(block, highlights=None, path="./temp.dot") -> str:
    """Write the block's op/var dataflow as a DOT digraph (reference
    debugger.py draw_block_graphviz)."""
    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}
    for i, v in enumerate(block.vars.values()):
        var_ids[v.name] = f"var_{i}"
        color = ', style=filled, fillcolor="yellow"' \
            if v.name in highlights else ""
        shape = "box" if getattr(v, "persistable", False) else "ellipse"
        lines.append(f'  var_{i} [label="{v.name}", shape={shape}{color}];')
    for j, op in enumerate(block.ops):
        lines.append(f'  op_{j} [label="{op.type}", shape=record, '
                     f'style=filled, fillcolor="lightgrey"];')
        for names in op.inputs.values():
            for n in names:
                if n in var_ids:
                    lines.append(f"  {var_ids[n]} -> op_{j};")
        for names in op.outputs.values():
            for n in names:
                if n in var_ids:
                    lines.append(f"  op_{j} -> {var_ids[n]};")
    lines.append("}")
    text = "\n".join(lines)
    with open(path, "w") as f:
        f.write(text)
    return text
